#!/usr/bin/env python
"""Evidence diff: compare two runs' telemetry dirs or BENCH_*.json
files with per-stage regression thresholds and a hardware fingerprint
check (docs/OBSERVABILITY.md "Evidence diff").

The ROADMAP's recurring failure mode is a TPU window spent re-deriving
"did we get faster" by hand. This CLI makes the re-baseline one
command: point it at the previous evidence and the fresh evidence, and
the output IS the regression report.

Inputs (auto-detected per argument):

- a **telemetry directory** (`--telemetry_dir` of a run): compares the
  last `metrics` snapshot's serving histograms + goodput fraction, the
  aggregated `request_trace` latency decomposition, and the program
  registry (`programs.jsonl`) row by row — per-program compile ms and
  FLOPs line up by (kind, key), so "this program got slower to build"
  and "this program changed shape" are separate findings. Two ISSUE 18
  artifacts ride along when present: the byte-stable per-tenant SLO
  summary (`tenant_slo.json`, loadgen's `write_tenant_slo`) diffs as a
  `tenant_slo` stage where attainment DOWN is worse, and flight-
  recorder `incident-*.json` bundles diff as per-kind counts in an
  `incidents` stage where ANY increase is a regression (counts, not
  percentages — one new replica_lost incident is a finding even from a
  zero base). Device-profile windows (`devprof.jsonl`, ISSUE 19) diff
  as a `devprof` stage from the last parsed window: per-op-family ms
  UP is worse, measured MFU / achieved comm bandwidth DOWN is worse,
  op counts and predicted comm bytes are neutral program-shape facts.
- a **bench result file** (the final JSON line of `bench.py`, e.g.
  `BENCH_r05.json`): compares numeric leaves per stage.

Direction is inferred from the metric name: `*_ms` / `*latency*` /
`p50|p99|max` / `compile`-style names regress UP; `*speedup*` /
`*throughput*` / `imgs_per_sec` / `mfu*` / `hit_rate`-style names
regress DOWN; other numbers are reported informationally and never
fail the comparison.

Hardware fingerprint: both sides' `platform`/`device_kind` (bench
`evidence` stamp — `bench.py --evidence` — or any registry row's
`fingerprint`) must match; differing fingerprints are different
experiments, not regressions, and exit 2 unless
`--allow-fingerprint-mismatch`.

Exit codes: 0 = comparable, no regression above threshold;
1 = at least one regression above threshold; 2 = incomparable
(fingerprint mismatch / unreadable input).

`--json` output is byte-stable (sorted keys, rounded floats, no
timestamps or absolute paths) — tested as a contract in
tests/test_tools.py.

Usage:
    python scripts/compare_runs.py runA/telemetry runB/telemetry
    python scripts/compare_runs.py BENCH_r03.json BENCH_r05.json \
        --threshold 0.10 --stage-threshold serve=0.25 --json
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

# metric-name direction heuristics (checked on the LAST path component
# and the full path, lowercase)
_UP_IS_WORSE = ("_ms", "latency", "_s", "p50", "p99", "max", "mean",
                "compile", "re_traces", "shed", "dropped", "wall",
                "step_time", "bytes", "incident", "faulted", "errors",
                "burn")
_DOWN_IS_WORSE = ("speedup", "throughput", "imgs_per_sec", "mfu",
                  "hit_rate", "fraction", "psnr", "occupancy",
                  "samples_per_s", "goodput", "rps", "attainment",
                  "achieved")
# pure identity/config numbers: never a finding in either direction
# (flops is here too: a FLOPs change means the PROGRAM changed shape —
# report it, but it is a different experiment, not a regression)
_NEUTRAL = ("seed", "count", "n_requests", "rate_hz", "batch", "steps",
            "rounds", "requests", "completed", "incarnation", "epoch",
            "devices", "world", "num_", "resolution", "nfe", "secs",
            "budget", "attempts", "image_size", "flops", "slo_ms",
            "schema_version",
            # planner decision bookkeeping (parallel/planner.py): how
            # many candidates were enumerated/pruned/probed describes
            # the SEARCH, not run quality — only the chosen plan's
            # probe/predicted ms (the "_ms" rule) regress
            "candidates", "pruned_", "probes", "cache_hit")
# neutral checked on the FULL path (before the generic "bytes"-is-worse
# heuristic): the static comm model (`collectives`,
# `comm_bytes_by_axis/<axis>`) describes the PROGRAM, not the run — a
# change means the program changed shape, which the lint comm budgets
# gate; here it is reported informationally, never as a regression
_NEUTRAL_PATH = ("comm_bytes", "collectives",
                 # a plan's HBM-fit estimate describes the CHOSEN plan
                 # (a deliberate memory/comm tradeoff), not a leak
                 "hbm_estimate")


def direction(path: str) -> int:
    """+1 = regression when candidate is HIGHER, -1 = regression when
    candidate is LOWER, 0 = informational."""
    p = path.lower()
    leaf = p.rsplit("/", 1)[-1]
    for frag in _NEUTRAL_PATH:
        if frag in p:
            return 0
    for frag in _NEUTRAL:
        if frag in leaf:
            return 0
    for frag in _DOWN_IS_WORSE:
        if frag in p:
            return -1
    for frag in _UP_IS_WORSE:
        if frag in p:
            return 1
    return 0


def _flatten(obj: Any, prefix: str = "") -> Dict[str, float]:
    out: Dict[str, float] = {}
    if isinstance(obj, dict):
        for k in obj:
            out.update(_flatten(obj[k], f"{prefix}/{k}" if prefix else str(k)))
    elif isinstance(obj, bool):
        pass                        # flags are not measurements
    elif isinstance(obj, (int, float)) and obj is not None:
        out[prefix] = float(obj)
    return out


def read_jsonl(path: str) -> List[Dict]:
    out: List[Dict] = []
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue            # torn tail
            if isinstance(rec, dict):
                out.append(rec)
    return out


def _pct(xs: List[float], q: float) -> Optional[float]:
    if not xs:
        return None
    s = sorted(xs)
    k = (len(s) - 1) * q
    lo, hi = int(k), min(int(k) + 1, len(s) - 1)
    return s[lo] + (s[hi] - s[lo]) * (k - lo)


# ---------------------------------------------------------------------------
# Loaders: one evidence dict per side — {"fingerprint", "stages"}
# ---------------------------------------------------------------------------

def load_bench(path: str) -> Dict[str, Any]:
    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    fp = dict(doc.get("evidence") or {})
    if "platform" not in fp and doc.get("platform"):
        fp["platform"] = doc["platform"]
    stages: Dict[str, Dict[str, float]] = {}
    headline = {k: v for k, v in doc.items()
                if isinstance(v, (int, float)) and not isinstance(v, bool)}
    if headline:
        stages["headline"] = _flatten(headline)
    for name, stage in (doc.get("stages") or {}).items():
        if isinstance(stage, dict) and stage.get("status") == "ok":
            stages[name] = _flatten(
                {k: v for k, v in stage.items() if k != "status"})
    return {"kind": "bench", "fingerprint": fp, "stages": stages}


def load_telemetry_dir(path: str) -> Dict[str, Any]:
    jsonl = os.path.join(path, "telemetry.jsonl")
    records = read_jsonl(jsonl) if os.path.exists(jsonl) else []
    metrics = [r for r in records if r.get("type") == "metrics"]
    traces = [r for r in records if r.get("type") == "request_trace"
              and r.get("outcome", "ok") == "ok"]
    stages: Dict[str, Dict[str, float]] = {}
    if metrics:
        last = metrics[-1]
        keep = {k: v for k, v in last.items()
                if isinstance(v, (int, float)) and not isinstance(v, bool)
                and k.startswith(("serving/", "goodput/", "phase/",
                                  "inference/", "diffcache/", "memory/",
                                  "train/"))}
        stages["metrics"] = _flatten(keep)
    if traces:
        agg: Dict[str, float] = {"count": float(len(traces))}
        for span in ("queue_ms", "compile_ms", "device_ms",
                     "latency_ms"):
            xs = [float(t.get(span, 0.0)) for t in traces]
            agg[f"{span}/p50"] = _pct(xs, 0.5)
            agg[f"{span}/p99"] = _pct(xs, 0.99)
        stages["request_traces"] = _flatten(agg)
    # per-tenant SLO artifact (loadgen's write_tenant_slo): attainment
    # DOWN is worse, per-tenant p50/p99 UP is worse
    slo_path = os.path.join(path, "tenant_slo.json")
    if os.path.exists(slo_path):
        try:
            with open(slo_path, "r", encoding="utf-8") as f:
                slo_doc = json.load(f)
        except (OSError, json.JSONDecodeError):
            slo_doc = {}
        tenants = slo_doc.get("tenants")
        if isinstance(tenants, dict) and tenants:
            stages["tenant_slo"] = _flatten(tenants)
    # flight-recorder bundles: per-kind incident counts (always
    # emitted, so a base with zero bundles still compares — the
    # candidate growing ANY kind from 0 is the finding)
    counts: Dict[str, float] = {"total": 0.0}
    for inc_path in sorted(glob.glob(
            os.path.join(path, "incident-*.json"))):
        try:
            with open(inc_path, "r", encoding="utf-8") as f:
                inc = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        kind = str(inc.get("kind") or "unknown")
        counts["total"] += 1.0
        counts[kind] = counts.get(kind, 0.0) + 1.0
    # keys carry the incidents/ prefix so direction() classifies them
    # (stage rows are compared by bare key, without the stage name)
    stages["incidents"] = {f"incidents/{k}": v
                           for k, v in counts.items()}
    # device-profile windows (devprof.jsonl, ISSUE 19): the LAST
    # successfully parsed window is the current device-time
    # attribution. Per-op-family ms regress UP ("attn got slower"),
    # measured MFU and achieved comm bandwidth regress DOWN, op counts
    # and predicted comm bytes are program-shape facts (neutral).
    dev_path = os.path.join(path, "devprof.jsonl")
    dev_rows = [r for r in (read_jsonl(dev_path)
                            if os.path.exists(dev_path) else [])
                if r.get("type") == "devprof"]
    ok_rows = [r for r in dev_rows if r.get("status") == "ok"]
    if ok_rows:
        last = ok_rows[-1]
        dp: Dict[str, Any] = {
            "windows": float(len(dev_rows)),
            "device_ms_per_step": last.get("device_ms_per_step"),
            "collective_ms": last.get("collective_ms"),
            "collective_count": last.get("collective_count"),
            "compute_ms": last.get("compute_ms"),
            "layout_copy_ms": last.get("layout_copy_ms"),
            "layout_copy_count": last.get("layout_copy_count"),
            "fusion_gap_ms": last.get("fusion_gap_ms"),
            "fusion_gap_count": last.get("fusion_gap_count"),
            "measured_mfu": last.get("measured_mfu"),
            "measured_flops_per_s": last.get("measured_flops_per_s"),
            "comm_measured_ms": last.get("comm_measured_ms"),
            # neutral via the comm_bytes path rule: predicted bytes
            # describe the PROGRAM, not the run
            "comm_bytes_predicted": last.get("comm_predicted_bytes"),
            "comm_achieved_bytes_per_s":
                last.get("comm_achieved_bytes_per_s"),
        }
        for fam, f in sorted((last.get("families") or {}).items()):
            if isinstance(f, dict):
                dp[f"families/{fam}_ms"] = f.get("ms")
                dp[f"families/{fam}_count"] = f.get("count")
        stages["devprof"] = {f"devprof/{k}": float(v)
                             for k, v in dp.items()
                             if isinstance(v, (int, float))
                             and not isinstance(v, bool)}
    fp: Dict[str, Any] = {}
    programs: Dict[str, Dict[str, float]] = {}
    from flaxdiff_tpu.telemetry.programs import (PROGRAMS_FILENAME,
                                                 read_registry)
    for row in read_registry(os.path.join(path, PROGRAMS_FILENAME)):
        if not fp and isinstance(row.get("fingerprint"), dict):
            fp = dict(row["fingerprint"])
        ident = f"{row.get('kind', '?')}::{row.get('key', '?')}"
        fields = {k: row[k] for k in ("compile_ms", "flops_jaxpr",
                                      "flops_cost", "bytes_cost",
                                      "hbm_peak_bytes", "collectives")
                  if isinstance(row.get(k), (int, float))}
        if isinstance(row.get("comm_bytes_by_axis"), dict):
            fields["comm_bytes_by_axis"] = row["comm_bytes_by_axis"]
        # planner decision rows (kind "plan"/"plan_infer") carry their
        # search/decision numbers as plan_* fields — diffable like any
        # other evidence (direction rules above)
        for k, v in row.items():
            if k.startswith("plan_") and isinstance(v, (int, float)) \
                    and not isinstance(v, bool):
                fields[k] = v
        programs[ident] = _flatten(fields)
    out = {"kind": "telemetry", "fingerprint": fp, "stages": stages}
    if programs:
        out["programs"] = programs
    return out


def load_side(path: str) -> Dict[str, Any]:
    if os.path.isdir(path):
        return load_telemetry_dir(path)
    return load_bench(path)


# ---------------------------------------------------------------------------
# Comparison
# ---------------------------------------------------------------------------

def compare_stage(base: Dict[str, float], cand: Dict[str, float],
                  threshold: float) -> List[Dict[str, Any]]:
    rows: List[Dict[str, Any]] = []
    for key in sorted(set(base) & set(cand)):
        b, c = base[key], cand[key]
        d = direction(key)
        if b == 0.0:
            delta = None
        else:
            delta = (c - b) / abs(b)
        regressed = False
        if d != 0 and delta is not None:
            regressed = (delta > threshold if d > 0
                         else delta < -threshold)
        rows.append({"metric": key, "base": b, "candidate": c,
                     "delta_pct": (round(delta * 100.0, 2)
                                   if delta is not None else None),
                     "direction": {1: "up_is_worse", -1: "down_is_worse",
                                   0: "info"}[d],
                     "regressed": regressed})
    return rows


def fingerprints_match(a: Dict[str, Any], b: Dict[str, Any]
                       ) -> Tuple[bool, str]:
    """Platform + device kind must agree when both sides carry them;
    a side with NO fingerprint is comparable-with-warning (older
    evidence predates the stamp)."""
    if not a or not b:
        return True, "missing on one side (pre-stamp evidence)"
    for field in ("platform", "device_kind"):
        va, vb = a.get(field), b.get(field)
        if va and vb and va != vb:
            return False, f"{field}: {va!r} != {vb!r}"
    return True, "ok"


def build_report(base_path: str, cand_path: str, threshold: float,
                 stage_thresholds: Dict[str, float]) -> Dict[str, Any]:
    base, cand = load_side(base_path), load_side(cand_path)
    fp_ok, fp_note = fingerprints_match(base["fingerprint"],
                                        cand["fingerprint"])
    report: Dict[str, Any] = {
        "base": os.path.basename(os.path.normpath(base_path)),
        "candidate": os.path.basename(os.path.normpath(cand_path)),
        "kind": {"base": base["kind"], "candidate": cand["kind"]},
        "fingerprint": {"match": fp_ok, "note": fp_note,
                        "base": base["fingerprint"],
                        "candidate": cand["fingerprint"]},
        "threshold": threshold,
        "stages": {},
        "regressions": [],
    }
    for name in sorted(set(base["stages"]) & set(cand["stages"])):
        th = stage_thresholds.get(name, threshold)
        rows = compare_stage(base["stages"][name], cand["stages"][name],
                             th)
        if name == "incidents":
            # counts, not percentages: one more replica_lost bundle is
            # a regression even from a zero base (where relative delta
            # is undefined and the generic threshold never fires)
            for r in rows:
                if r["direction"] == "up_is_worse":
                    r["regressed"] = r["candidate"] > r["base"]
        report["stages"][name] = {"threshold": th, "rows": rows}
        for r in rows:
            if r["regressed"]:
                report["regressions"].append(
                    {"stage": name, **r})
    only_base = sorted(set(base["stages"]) - set(cand["stages"]))
    only_cand = sorted(set(cand["stages"]) - set(base["stages"]))
    if only_base or only_cand:
        report["uncompared_stages"] = {"base_only": only_base,
                                       "candidate_only": only_cand}
    if "programs" in base and "programs" in cand:
        pb, pc = base["programs"], cand["programs"]
        prog_rows: List[Dict[str, Any]] = []
        for ident in sorted(set(pb) & set(pc)):
            th = stage_thresholds.get("programs", threshold)
            for r in compare_stage(pb[ident], pc[ident], th):
                r["program"] = ident
                prog_rows.append(r)
                if r["regressed"]:
                    report["regressions"].append(
                        {"stage": "programs", **r})
        report["programs"] = {
            "compared": len(set(pb) & set(pc)),
            "base_only": sorted(set(pb) - set(pc)),
            "candidate_only": sorted(set(pc) - set(pb)),
            "rows": prog_rows,
        }
    report["ok"] = fp_ok and not report["regressions"]
    return report


def _stable(obj):
    if isinstance(obj, float):
        return round(obj, 4)
    if isinstance(obj, dict):
        return {k: _stable(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_stable(v) for v in obj]
    return obj


def render_text(report: Dict[str, Any]) -> str:
    lines = [f"evidence diff: {report['base']} -> {report['candidate']}"]
    fp = report["fingerprint"]
    lines.append(f"fingerprint: {'MATCH' if fp['match'] else 'MISMATCH'}"
                 f" ({fp['note']})")
    for name in sorted(report["stages"]):
        st = report["stages"][name]
        flagged = [r for r in st["rows"] if r["regressed"]]
        moved = [r for r in st["rows"]
                 if r["delta_pct"] is not None
                 and abs(r["delta_pct"]) >= st["threshold"] * 100.0
                 and r["direction"] != "info"]
        lines.append(f"== {name} ({len(st['rows'])} shared metrics, "
                     f"threshold {st['threshold']:.0%}) ==")
        for r in (flagged or moved[:8]):
            mark = "REGRESSION" if r["regressed"] else "improved"
            pct = ("new" if r["delta_pct"] is None
                   else f"{r['delta_pct']:+.1f}%")
            lines.append(
                f"  {r['metric']:<44s} {r['base']:>12.4g} -> "
                f"{r['candidate']:>12.4g}  ({pct}) {mark}")
        if not flagged and not moved:
            lines.append("  (no movement beyond threshold)")
    progs = report.get("programs")
    if progs:
        lines.append(f"== programs ({progs['compared']} shared) ==")
        for r in progs["rows"]:
            if r["regressed"]:
                lines.append(
                    f"  {r['program']}\n    {r['metric']}: "
                    f"{r['base']:.4g} -> {r['candidate']:.4g} "
                    f"({r['delta_pct']:+.1f}%) REGRESSION")
        if progs["base_only"] or progs["candidate_only"]:
            lines.append(f"  only in base: {len(progs['base_only'])}, "
                         f"only in candidate: "
                         f"{len(progs['candidate_only'])}")
    n = len(report["regressions"])
    lines.append(f"verdict: "
                 + ("INCOMPARABLE (fingerprint mismatch)"
                    if not fp["match"] else
                    (f"{n} regression(s) above threshold" if n
                     else "no regressions above threshold")))
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="diff two runs' evidence (telemetry dirs or bench "
                    "JSON) with regression thresholds")
    ap.add_argument("base", help="baseline telemetry dir or BENCH json")
    ap.add_argument("candidate", help="candidate telemetry dir or "
                                      "BENCH json")
    ap.add_argument("--threshold", type=float, default=0.10,
                    help="default relative regression threshold "
                         "(0.10 = 10%%)")
    ap.add_argument("--stage-threshold", action="append", default=[],
                    metavar="STAGE=PCT",
                    help="per-stage override, e.g. serve=0.25 "
                         "(repeatable; 'programs' targets the registry "
                         "comparison)")
    ap.add_argument("--allow-fingerprint-mismatch", action="store_true",
                    help="compare across hardware anyway (exit codes "
                         "then reflect regressions only)")
    ap.add_argument("--json", action="store_true",
                    help="emit the byte-stable JSON report instead of "
                         "text")
    args = ap.parse_args(argv)

    stage_thresholds: Dict[str, float] = {}
    for spec in args.stage_threshold:
        if "=" not in spec:
            ap.error(f"--stage-threshold wants STAGE=PCT, got {spec!r}")
        name, _, val = spec.partition("=")
        stage_thresholds[name] = float(val)

    try:
        report = build_report(args.base, args.candidate, args.threshold,
                              stage_thresholds)
    except (OSError, json.JSONDecodeError, ValueError) as e:
        print(f"incomparable: {e}", file=sys.stderr)
        return 2

    if args.json:
        print(json.dumps(_stable(report), sort_keys=True, indent=1))
    else:
        print(render_text(report))
    if not report["fingerprint"]["match"] \
            and not args.allow_fingerprint_mismatch:
        return 2
    return 1 if report["regressions"] else 0


if __name__ == "__main__":
    sys.exit(main())

"""Elastic-world chaos suite: REAL 2-process host loss, late join, and
anomaly-quorum eviction (ISSUE 12 acceptance scenarios).

Unlike tests/test_multiprocess.py these workers run WITHOUT
`jax.distributed` — its coordinator dies with process 0 and its world
is fixed at initialize(), the two assumptions an elastic world cannot
make. Coordination rides a FileTransport over a shared directory
(identical protocol/timeout semantics to the KV-service backend), each
host owns its local devices + its own checkpoint dir, and ONE shared
control ledger records commits and membership transitions.

Orphan safety: every phase joins/kills its children in `finally` (the
multiprocess-suite convention — an orphaned worker wedges later test
files into fake timeouts on this single-CPU box).
"""
import json
import os
import subprocess
import sys
import time

import pytest

WORKER = os.path.join(os.path.dirname(__file__), "multiprocess_worker.py")

pytestmark = [pytest.mark.chaos, pytest.mark.multiprocess]


def _launch(phase: str, proc_id: int, ckpt_root: str):
    env = os.environ.copy()
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    return subprocess.Popen(
        [sys.executable, WORKER, phase, str(proc_id), "0", ckpt_root],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env)


def _finish(proc, phase, i, timeout, expect_rc=0, expect_result=True):
    out, err = proc.communicate(timeout=timeout)
    assert proc.returncode == expect_rc, (
        f"{phase} proc {i} rc={proc.returncode} (wanted {expect_rc})\n"
        f"stdout:{out[-2000:]}\nstderr:{err[-2000:]}")
    if not expect_result:
        return None
    lines = [ln for ln in out.splitlines() if ln.startswith("RESULT ")]
    assert lines, f"{phase} proc {i} printed no RESULT line:\n{out[-2000:]}"
    return json.loads(lines[-1][len("RESULT "):])


def test_kill_one_mid_run_survivor_shrinks_and_trains(tmp_path):
    """Kill-one-mid-run: rank 1 dies hard (os._exit, no vote) at step 4;
    rank 0's commit barrier times out, it commits a `world_changed`
    shrink in the ledger, restores the consensus step 2, re-shards its
    data pipeline to (rank 0, world 1), and keeps training — history
    attributes the transition to `elastic_shrink` badput with a
    reclaimed estimate, and there is NO coordination_lost exit."""
    root = str(tmp_path / "elastic")
    procs = [_launch("elastic_kill", i, root) for i in range(2)]
    try:
        # rank 1 self-destructs with rc 17 and never prints a RESULT
        _finish(procs[1], "elastic_kill", 1, timeout=420, expect_rc=17,
                expect_result=False)
        r0 = _finish(procs[0], "elastic_kill", 0, timeout=420)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()

    assert r0["coordination_lost"] is False
    assert len(r0["elastic"]) == 1
    tr = r0["elastic"][0]
    assert tr["kind"] == "shrink" and tr["world"] == 1 and tr["step"] == 2
    assert tr["reclaimed_s"] >= 0.0
    assert r0["goodput_badput"].get("elastic_shrink", 0.0) > 0.0
    # the ledger carries the membership history and the shrunken-world
    # commits: step 2 committed by the world of 2, later steps by the
    # world of 1 — and the survivor made progress (>= 4 steps) past the
    # consensus step after the transition
    wc = r0["world_changes"]
    assert len(wc) == 1 and wc[0]["change"] == "shrink"
    assert wc[0]["world"] == 1 and wc[0]["members"] == [0]
    assert r0["commit_worlds"]["2"] == 2
    post = [int(s) for s in r0["committed"] if int(s) > 2]
    assert post, f"no committed step after the shrink: {r0['committed']}"
    assert all(r0["commit_worlds"][str(s)] == 1 for s in post)
    assert r0["state_step"] >= 6     # >= 4 steps past the restored 2
    # the data pipeline was re-sharded around the smaller world
    assert [0, 1] in r0["factory_calls"]


def test_late_joiner_readmitted_and_worlds_commit_in_lockstep(tmp_path):
    """Late-join: rank 0 trains alone; rank 1 launches late, parks via
    request_join, is admitted at a commit boundary (`world_changed`
    grow entry), restores the consensus step from rank 0's shard dir,
    and both hosts then commit the SAME final step with world 2
    recorded in the commit entries."""
    root = str(tmp_path / "elastic")
    p0 = _launch("elastic_join", 0, root)
    procs = [p0]
    try:
        time.sleep(5.0)     # rank 1 is genuinely LATE
        p1 = _launch("elastic_join", 1, root)
        procs.append(p1)
        r0 = _finish(p0, "elastic_join", 0, timeout=420)
        r1 = _finish(p1, "elastic_join", 1, timeout=420)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()

    assert r0["coordination_lost"] is False
    assert r1["coordination_lost"] is False
    # the grow transition is in the shared ledger exactly once
    grows = [w for w in r0["world_changes"] if w["change"] == "grow"]
    assert len(grows) == 1
    assert grows[0]["members"] == [0, 1] and grows[0]["world"] == 2
    assert r1["joined_at"] == grows[0]["step"]
    assert r1["join_world"] == 2
    # both ended as members of the same world...
    assert r0["members"] == r1["members"] == [0, 1]
    # ...and committed the same final step, with the grown world size
    # recorded by the commit round itself
    assert r0["committed"] == r1["committed"]
    final = r0["committed"][-1]
    assert final == 16 == r0["state_step"] == r1["state_step"]
    assert r0["commit_worlds"][str(final)] == 2
    # pre-join commits were a world of 1
    assert r0["commit_worlds"]["2"] == 1
    # rank 0's incumbent fit observed the re-admission
    assert any(e["kind"] == "grow" for e in r0["elastic"])
    # both re-sharded to (rank, 2)
    assert [0, 2] in r0["factory_calls"]
    assert r1["factory_calls"] == []    # joiner started sharded already


def test_divergent_anomaly_quorum_evicts_outlier(tmp_path):
    """Divergent-anomaly: rank 1's params are poisoned (numerics.nan
    chaos site, one host only); at the numerics cadence the hard
    anomaly becomes a pod VOTE — the 1-of-2 outlier is evicted (ledger
    `quorum` + `world_changed` entries), rank 0 keeps training
    untouched in a world of 1, and rank 1 leaves WITHOUT committing."""
    root = str(tmp_path / "elastic")
    procs = [_launch("elastic_quorum", i, root) for i in range(2)]
    try:
        r0 = _finish(procs[0], "elastic_quorum", 0, timeout=420)
        r1 = _finish(procs[1], "elastic_quorum", 1, timeout=420)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()

    # rank 1 saw its own eviction and stopped; its state never committed
    assert r1["quorum_evicted"] is True
    assert r1["quorum"] == ["evicted"]
    # rank 0 adopted the eviction, never rolled back, and kept going
    assert r0["quorum"] == ["evict"]
    assert r0["quorum_evicted"] is False
    assert r0["coordination_lost"] is False
    assert r0["members"] == [0]
    assert len(r0["elastic"]) == 1 and r0["elastic"][0]["kind"] == "evict"
    # the shared ledger records the vote and the transition
    q = r0["quorum_entries"]
    assert len(q) == 1 and q[0]["decision"] == "evict"
    assert q[0]["votes"] == {"0": False, "1": True}
    wc = [w for w in r0["world_changes"] if w["change"] == "evict"]
    assert len(wc) == 1 and wc[0]["members"] == [0]
    # the survivor committed steps after the eviction, as a world of 1
    assert r0["committed"], "survivor committed nothing"
    assert [0, 1] in r0["factory_calls"]

"""Unified retry policy: exponential backoff + deterministic-seedable
jitter + total-time deadline + non-retryable error classification.

Replaces the bespoke loops that grew in the data layer (fixed
`time.sleep(0.1)` in `default_url_fetcher`, which burned the full retry
budget on HTTP 404s) and wraps `Checkpointer.save` / logger pushes, so
every transient-fault path in the framework backs off the same way and
reports through the same event stream.
"""
from __future__ import annotations

import dataclasses
import random
import time
from typing import Callable, Optional, Tuple

from .events import EventLog, global_event_log

# HTTP statuses that will not succeed on retry (client errors minus 408
# request-timeout and 429 too-many-requests, which are transient).
NON_RETRYABLE_HTTP = frozenset(
    {400, 401, 403, 404, 405, 406, 410, 411, 413, 414, 415, 422, 451})


def _http_code(exc: BaseException) -> Optional[int]:
    code = getattr(exc, "code", None)
    if isinstance(code, int):
        return code
    resp = getattr(exc, "response", None)           # requests-style
    return getattr(resp, "status_code", None) if resp is not None else None


def default_classifier(exc: BaseException) -> bool:
    """True if `exc` is worth retrying.

    Retryable: I/O and network faults (OSError covers URLError, socket
    timeouts, ConnectionError), plus HTTP 5xx/408/429. Non-retryable:
    HTTP 4xx client errors, programming errors (TypeError/ValueError/
    KeyError/AttributeError), and control-flow exceptions.
    """
    if isinstance(exc, (KeyboardInterrupt, SystemExit, GeneratorExit,
                        StopIteration, AssertionError)):
        return False
    code = _http_code(exc)
    if code is not None:
        return code not in NON_RETRYABLE_HTTP
    if isinstance(exc, (TypeError, ValueError, KeyError, AttributeError,
                        IndexError, NotImplementedError)):
        return False
    return True


class RetryError(RuntimeError):
    """Raised when the budget is exhausted; chains the last failure."""

    def __init__(self, site: str, attempts: int, last: BaseException):
        super().__init__(
            f"{site}: gave up after {attempts} attempt(s): {last!r}")
        self.site = site
        self.attempts = attempts
        self.last = last


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Exponential backoff with jitter and an optional wall-clock deadline.

    Delay before attempt k (k >= 2) is
    `min(base_delay * growth**(k-2), max_delay)` scaled by a jitter
    factor drawn uniformly from [1 - jitter, 1]. `seed=None` uses
    process randomness; tests pass a seed (and a fake `sleep`) for
    exact replay.
    """
    max_attempts: int = 3
    base_delay: float = 0.1
    growth: float = 2.0
    max_delay: float = 5.0
    jitter: float = 0.5
    deadline: Optional[float] = None          # total seconds across attempts
    classifier: Callable[[BaseException], bool] = default_classifier
    seed: Optional[int] = None
    sleep: Callable[[float], None] = time.sleep
    clock: Callable[[], float] = time.monotonic
    # server-directed backoff floor: given the exception, return a
    # minimum delay in seconds (or None). Lets HTTP 429/503 honor a
    # `Retry-After` header instead of retrying into a closed door; the
    # floor is capped at max_delay so a hostile header cannot stall a
    # worker unboundedly.
    delay_floor_from: Optional[
        Callable[[BaseException], Optional[float]]] = None

    def __post_init__(self):
        if self.max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        if not (0.0 <= self.jitter <= 1.0):
            raise ValueError("jitter must be in [0, 1]")

    def delays(self) -> Tuple[float, ...]:
        """The backoff schedule (pre-jitter) — one delay per retry."""
        return tuple(min(self.base_delay * self.growth ** i, self.max_delay)
                     for i in range(self.max_attempts - 1))

    def call(self, fn: Callable, *args,
             site: str = "retry",
             event_log: Optional[EventLog] = None,
             step: Optional[int] = None,
             **kwargs):
        """Run `fn(*args, **kwargs)` under this policy.

        Non-retryable errors propagate immediately (classifier says no).
        Exhaustion raises `RetryError` chaining the last error. Every
        re-attempt records a `retry` event; exhaustion records
        `retry_exhausted`.
        """
        events = event_log if event_log is not None else global_event_log()
        rng = random.Random(self.seed)
        start = self.clock()
        last: Optional[BaseException] = None
        for attempt in range(1, self.max_attempts + 1):
            try:
                return fn(*args, **kwargs)
            except BaseException as e:  # noqa: BLE001 — classifier decides
                last = e
                if not self.classifier(e):
                    raise
                if attempt >= self.max_attempts:
                    break
                delay = min(self.base_delay * self.growth ** (attempt - 1),
                            self.max_delay)
                if self.jitter:
                    delay *= 1.0 - self.jitter * rng.random()
                if self.delay_floor_from is not None:
                    floor = self.delay_floor_from(e)
                    if floor is not None:
                        delay = max(delay, min(floor, self.max_delay))
                if (self.deadline is not None
                        and self.clock() - start + delay > self.deadline):
                    events.record("retry_exhausted", site,
                                  detail=f"deadline {self.deadline}s hit "
                                         f"after {attempt} attempt(s): {e!r}",
                                  step=step)
                    raise RetryError(site, attempt, e) from e
                events.record(
                    "retry", site,
                    detail=f"attempt {attempt}/{self.max_attempts} failed "
                           f"({e!r}); backing off {delay:.3f}s",
                    step=step)
                self.sleep(delay)
        assert last is not None
        events.record("retry_exhausted", site,
                      detail=f"{self.max_attempts} attempt(s): {last!r}",
                      step=step)
        raise RetryError(site, self.max_attempts, last) from last

    def wrap(self, fn: Callable, site: str = "retry",
             event_log: Optional[EventLog] = None) -> Callable:
        """`fn` curried under this policy (decorator form)."""
        def wrapped(*args, **kwargs):
            return self.call(fn, *args, site=site, event_log=event_log,
                             **kwargs)
        wrapped.__name__ = getattr(fn, "__name__", "wrapped")
        return wrapped

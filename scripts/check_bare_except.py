#!/usr/bin/env python
"""Static gate: no NEW silent exception swallowing.

The observability layer's worst enemy is `except Exception: pass` — a
failure that leaves no counter, no event, no log line is invisible to
the telemetry/goodput accounting this repo now runs on. This pass walks
the AST of every production Python file and fails on exception handlers
that swallow silently: a handler catching everything (bare `except`,
`except Exception`, `except BaseException`) whose body does NOTHING
(only `pass`/`...`) — no event record, no logging, no re-raise, no
fallback value.

Pre-existing offenders are grandfathered in ALLOWLIST (file -> max
count); new ones fail CI (wired as a tier-1 check in
tests/test_tools.py). Shrink the allowlist when you fix one — a file
dropping below its budget tightens it automatically? No: budgets are
MAXIMA; lower actual counts pass and the list should then be edited
down (the failure message says so).

Usage:
    python scripts/check_bare_except.py            # repo default roots
    python scripts/check_bare_except.py --root DIR # scan one tree
"""
from __future__ import annotations

import argparse
import ast
import os
import sys
from typing import Dict, List, Optional, Tuple

# Grandfathered silent handlers (relpath -> max allowed). Each entry is
# debt: fix the site to record a resilience event (or at least log),
# then delete the line here.
ALLOWLIST: Dict[str, int] = {
    "flaxdiff_tpu/data/sharded_source.py": 1,   # best-effort len probe
    "flaxdiff_tpu/data/packed_records.py": 1,   # optional index sidecar
    "scripts/demo_sfc.py": 1,                   # optional matplotlib
    "bench.py": 1,                              # best-effort trace close
}

# Production roots scanned by default (tests may legitimately swallow
# in teardown helpers; they are reviewed, not gated).
DEFAULT_ROOTS = ("flaxdiff_tpu", "scripts", "train.py", "bench.py")


def _catches_everything(handler: ast.ExceptHandler) -> bool:
    if handler.type is None:
        return True
    t = handler.type
    names = []
    if isinstance(t, ast.Name):
        names = [t.id]
    elif isinstance(t, ast.Tuple):
        names = [e.id for e in t.elts if isinstance(e, ast.Name)]
    return any(n in ("Exception", "BaseException") for n in names)


def _is_silent(handler: ast.ExceptHandler) -> bool:
    for stmt in handler.body:
        if isinstance(stmt, ast.Pass):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value,
                                                     ast.Constant):
            continue        # docstring or bare `...`
        return False        # does SOMETHING: logs, records, returns, ...
    return True


def scan_file(path: str) -> List[Tuple[int, str]]:
    """(lineno, snippet) of silent catch-all handlers in one file."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            src = f.read()
        tree = ast.parse(src, filename=path)
    except (OSError, SyntaxError) as e:
        return [(0, f"unparseable: {e}")]
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.ExceptHandler) \
                and _catches_everything(node) and _is_silent(node):
            out.append((node.lineno,
                        ast.unparse(node.type) if node.type else "bare"))
    return out


def iter_py_files(root: str):
    if os.path.isfile(root):
        yield root
        return
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames
                       if d not in ("__pycache__", ".git")]
        for fn in filenames:
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="fail on new silent except-Exception-pass handlers")
    ap.add_argument("--root", default=None,
                    help="scan this file/tree with an EMPTY allowlist "
                         "(default: the repo's production roots with "
                         "the grandfathered allowlist)")
    args = ap.parse_args(argv)

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if args.root is not None:
        roots, allow, base = [args.root], {}, os.path.dirname(
            os.path.abspath(args.root)) or "."
    else:
        roots = [os.path.join(repo, r) for r in DEFAULT_ROOTS]
        allow, base = ALLOWLIST, repo

    failures: List[str] = []
    shrinkable: List[str] = []
    for root in roots:
        if not os.path.exists(root):
            continue
        per_file: Dict[str, List[Tuple[int, str]]] = {}
        for path in iter_py_files(root):
            hits = scan_file(path)
            if hits:
                per_file[os.path.relpath(path, base)] = hits
        for rel, hits in sorted(per_file.items()):
            budget = allow.get(rel, 0)
            if len(hits) > budget:
                for lineno, what in hits:
                    failures.append(
                        f"{rel}:{lineno}: silent `except {what}` with "
                        f"empty body ({len(hits)} in file, allowlist "
                        f"budget {budget}) — record a resilience event "
                        f"or log before swallowing")
            elif len(hits) < budget:
                shrinkable.append(
                    f"{rel}: {len(hits)} silent handler(s), budget "
                    f"{budget} — shrink ALLOWLIST in "
                    f"scripts/check_bare_except.py")
    for msg in shrinkable:
        print(f"note: {msg}")
    if failures:
        print("\n".join(failures), file=sys.stderr)
        print(f"\n{len(failures)} new silent exception handler(s). "
              f"A swallowed failure is invisible to telemetry — see "
              f"docs/OBSERVABILITY.md.", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())

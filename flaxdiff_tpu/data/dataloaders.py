"""Grain loader assembly + collation.

Capability parity with reference flaxdiff/data/dataloaders.py:261-640
(get_dataset_grain: IndexSampler sharded by jax process, worker processes,
shape-normalizing collate with fallback dummy batches, per-process batch
slicing). The trainer consumes host-local numpy batches and builds global
arrays itself (DiffusionTrainer.put_batch), so loaders here stop at the
host boundary — no per-step device sync.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Iterator, Optional, Tuple

import jax
import numpy as np

from .sources.base import MediaDataset


def collate(samples, sample_key: str = "image") -> Dict[str, Any]:
    """Stack sample dicts into a batch dict; tokenized text stacks per
    sub-key (reference dataloaders.py:85-252)."""
    if not samples:
        raise ValueError("empty batch")
    batch: Dict[str, Any] = {}
    first = samples[0]
    for key in first:
        vals = [s[key] for s in samples]
        if isinstance(first[key], dict):
            batch[key] = {k: np.stack([v[k] for v in vals])
                          for k in first[key]}
        elif isinstance(first[key], str):
            batch[key] = list(vals)
        else:
            batch[key] = np.stack(vals)
    return batch


def _destring(batch: Dict[str, Any]) -> Dict[str, Any]:
    """Convert numpy unicode arrays (grain's stacked strings) to lists."""
    def fix(v):
        if isinstance(v, dict):
            return {k: fix(x) for k, x in v.items()}
        if isinstance(v, np.ndarray) and v.dtype.kind in ("U", "S"):
            return [str(s) for s in v.tolist()]
        return v
    return {k: fix(v) for k, v in batch.items()}


def to_trainer_batch(batch: Dict[str, Any]) -> Dict[str, Any]:
    """Rename the media key to the trainer's contract: train_step reads
    batch["sample"] (train_step.py:57) and conditioning under "cond"."""
    out: Dict[str, Any] = {}
    for key, v in batch.items():
        if key in ("image", "video"):
            out["sample"] = v
        elif key == "text" and not isinstance(v, list):
            out.setdefault("cond", {})["text"] = v
        else:
            out[key] = v
    return out


def fallback_batch(reference_batch: Dict[str, Any]) -> Dict[str, Any]:
    """Zero-filled batch with the same structure — injected when a batch
    fails to decode (reference dataloaders.py:203-247)."""
    def zero(v):
        if isinstance(v, dict):
            return {k: zero(x) for k, x in v.items()}
        if isinstance(v, list):
            return [""] * len(v)
        return np.zeros_like(v)
    return {k: zero(v) for k, v in reference_batch.items()}


@dataclasses.dataclass
class GrainLoader:
    """Restartable epoch iterator over a grain DataLoader. Batches come
    out in trainer contract form ({"sample": ..., "cond"/"text": ...})."""

    make_loader: Callable[[int], Any]     # seed -> grain DataLoader
    batches_per_epoch: int

    def __call__(self, seed: int = 0) -> Iterator[Dict[str, Any]]:
        last_good: Optional[Dict[str, Any]] = None
        epoch = 0
        while True:
            it = iter(self.make_loader(seed + epoch))
            produced = 0
            while True:
                try:
                    batch = to_trainer_batch(_destring(next(it)))
                except StopIteration:
                    break
                except Exception:
                    # decode/transform failure: keep the loop fed
                    # (reference dataloaders.py:203-247)
                    if last_good is None:
                        continue
                    batch = fallback_batch(last_good)
                last_good = batch
                produced += 1
                yield batch
            if produced == 0 and last_good is None:
                # fewer records than one (drop_remainder) batch: an
                # epoch yields nothing and the loop would spin forever
                raise ValueError(
                    "grain epoch produced no batches — dataset smaller "
                    "than one batch (drop_remainder)? records per "
                    f"process insufficient for the local batch size")
            epoch += 1


def get_dataset_grain(dataset: MediaDataset,
                      batch_size: int,
                      image_size: int = 64,
                      worker_count: int = 0,
                      seed: int = 0,
                      num_epochs: Optional[int] = None,
                      drop_remainder: bool = True,
                      augment_kwargs: Optional[dict] = None,
                      worker_buffer_size: int = 1,
                      read_threads: Optional[int] = None,
                      read_buffer_size: Optional[int] = None) -> Dict[str, Any]:
    """Assemble the sharded grain pipeline for one MediaDataset.

    Returns {"train": callable -> iterator, "train_len": n_records,
    "local_batch_size": per-process batch} (reference
    dataloaders.py:261-349). worker_buffer_size / read_threads /
    read_buffer_size are the grain throughput knobs the reference tunes
    from its CLI (reference training.py:84-99: 32 workers / 140 read
    threads / read buffer 96 / worker buffer 100 at corpus scale).
    """
    import grain.python as pygrain

    source = dataset.get_source()
    transform = dataset.get_augmenter(
        image_size=image_size, **(augment_kwargs or {}))
    filt = dataset.augmenter.create_filter()

    if batch_size % jax.process_count():
        raise ValueError(
            f"batch {batch_size} not divisible by {jax.process_count()} "
            "processes")
    local_bs = batch_size // jax.process_count()

    class _Map(pygrain.RandomMapTransform):
        def random_map(self, record, rng: np.random.Generator):
            return transform(record, rng=rng)

    ops = []
    if filt is not None:
        class _Filter(pygrain.FilterTransform):
            def filter(self, record) -> bool:
                return filt(record)
        ops.append(_Filter())
    ops.append(_Map())
    # grain's Batch stacks every leaf (strings become <U numpy arrays);
    # GrainLoader converts string arrays back to lists downstream.
    ops.append(pygrain.Batch(batch_size=local_bs,
                             drop_remainder=drop_remainder))

    def make_loader(epoch_seed: int,
                    shard: Optional[Tuple[int, int]] = None):
        # default: launch-time jax process world; an explicit
        # (rank, size) shard override re-shards the index sampler for
        # a post-shrink elastic world (the `reshard` factory below)
        shard_options = (
            pygrain.ShardOptions(shard_index=shard[0],
                                 shard_count=shard[1],
                                 drop_remainder=True)
            if shard is not None
            else pygrain.ShardByJaxProcess(drop_remainder=True))
        sampler = pygrain.IndexSampler(
            num_records=len(source),
            shuffle=True,
            seed=epoch_seed,
            num_epochs=1,
            shard_options=shard_options,
        )
        read_options = None
        if read_threads is not None or read_buffer_size is not None:
            read_options = pygrain.ReadOptions(
                **({"num_threads": read_threads}
                   if read_threads is not None else {}),
                **({"prefetch_buffer_size": read_buffer_size}
                   if read_buffer_size is not None else {}))
        return pygrain.DataLoader(
            data_source=source,
            sampler=sampler,
            operations=ops,
            worker_count=worker_count,
            worker_buffer_size=worker_buffer_size,
            read_options=read_options,
        )

    n = len(source) // jax.process_count()

    def reshard(rank: int, size: int) -> GrainLoader:
        """Rebuild the grain pipeline for a changed world: the index
        sampler re-shards over the surviving (rank, size) instead of
        the launch-time jax process world, so an elastic shrink
        re-partitions the dataset across survivors with no records
        orphaned on dead hosts. Batch geometry is unchanged — each
        survivor still emits `local_batch_size` batches."""
        per = len(source) // max(size, 1)
        return GrainLoader(
            lambda es: make_loader(es, shard=(rank, size)),
            max(per // local_bs, 1))

    return {
        "train": GrainLoader(make_loader, max(n // local_bs, 1)),
        "train_len": len(source),
        "local_batch_size": local_bs,
        "global_batch_size": batch_size,
        "reshard": reshard,
    }


def make_batch_iterator(images: np.ndarray,
                        batch_size: int,
                        labels=None,
                        seed: int = 0) -> Iterator[Dict[str, Any]]:
    """Minimal in-memory infinite batch iterator (no grain) for quick runs
    and benchmarks."""
    rng = np.random.default_rng(seed)
    n = len(images)
    while True:
        idx = rng.integers(0, n, size=batch_size)
        batch = {"sample": np.asarray(images[idx])}
        if labels is not None:
            batch["text"] = [labels[i] for i in idx]
        yield batch

"""Jaxpr-level rules: invariants only visible in the traced program.

The AST pass sees what a reviewer sees; these rules see what XLA sees.
`programs.py` traces the REAL hot programs (the train step, its
monitored twin, the serving chunk programs) with `jax.make_jaxpr` on
CPU — tracing only, nothing compiles — and each rule walks the jaxpr
recursively the way `profiling.jaxpr_flops` does (pjit / custom-vjp /
remat sub-jaxprs descended, scan bodies multiplied by trip count, cond
branches treated alternatively).

  rng-key-reuse   a PRNG key consumed by >=2 random draws (or split
                  twice) without an intervening split/fold_in — the
                  serving layer's bit-identity contract dies here
                  (two "independent" noises become equal)
  callback-leak   pure_callback / io_callback / debug_callback inside
                  a jitted hot program — each is a host round-trip the
                  sync-free pipeline exists to avoid
  bf16-upcast     budgeted audit of bf16 -> f32 convert_element_type
                  traffic (report, not verdict: deliberate f32
                  accumulation is correct; its TOTAL should only ever
                  change deliberately)
"""
from __future__ import annotations

import itertools
from collections import Counter, defaultdict
from typing import Dict, List, Optional, Tuple

from .framework import (UPCAST_BUDGET, UPCAST_DEFAULT_BUDGET, Finding,
                        GraphRule, register)

# ---------------------------------------------------------------------------
# generic recursive eqn iteration (callback + upcast walkers)
# ---------------------------------------------------------------------------


def _sub_jaxprs(params):
    """Every (closed)jaxpr nested in an eqn's params (the
    profiling._iter_subjaxprs idiom)."""
    for v in params.values():
        vs = v if isinstance(v, (tuple, list)) else (v,)
        for x in vs:
            if hasattr(x, "jaxpr") and hasattr(x, "consts"):
                yield x.jaxpr          # ClosedJaxpr
            elif hasattr(x, "eqns"):
                yield x                # raw Jaxpr


def iter_eqns(jaxpr, mult: int = 1):
    """Yield (eqn, multiplier) over the whole nest; scan bodies carry
    their trip count, cond branches each yield at the parent multiplier
    (at most one executes — callers wanting max-branch semantics can
    group on branch identity, the audits here just sum, which is the
    conservative direction for "is this present at all")."""
    for eqn in jaxpr.eqns:
        yield eqn, mult
        sub_mult = mult
        if eqn.primitive.name == "scan":
            sub_mult = mult * int(eqn.params.get("length", 1) or 1)
        for sub in _sub_jaxprs(eqn.params):
            yield from iter_eqns(sub, sub_mult)


# ---------------------------------------------------------------------------
# rng-key-reuse: identity tracking through the typed-key primitives
# ---------------------------------------------------------------------------

class _KeyUse:
    """Per-program key-consumption account.

    Tokens identify key VALUES: a fresh token per program input /
    unknown producer, propagated through random_wrap/random_unwrap and
    shape-only ops, and through `slice` by its static start/limit (two
    identical slices of one split result are the same child key; two
    different slices are different children). Consumers:

      draws   random_bits (every jax.random sampler bottoms out here)
      splits  random_split (a second split of the same key yields the
              SAME children — as much a reuse as a double draw)

    random_fold_in derives a fresh key and is NOT a consumption: folding
    one key with distinct data is the sanctioned per-step derivation
    (train_step folds state.rng with the step counter). Folding twice
    with the SAME data is undetectable statically — documented
    limitation.
    """

    def __init__(self):
        self.draws: Counter = Counter()
        self.splits: Counter = Counter()
        self.sites: Dict = defaultdict(list)
        self._fresh = itertools.count()

    def fresh(self, tag: str = "t"):
        return (tag, next(self._fresh))

    def consume(self, tok, kind: str, where: str):
        if tok is None:         # literal operand: no identity to reuse
            return
        (self.draws if kind == "draw" else self.splits)[tok] += 1
        self.sites[tok].append(where)

    def merge_max(self, branches: List["_KeyUse"]) -> None:
        """cond semantics: one branch executes — a key consumed once in
        EACH branch is consumed once, not len(branches) times."""
        for field in ("draws", "splits"):
            mine = getattr(self, field)
            toks = set()
            for b in branches:
                toks |= set(getattr(b, field))
            for tok in toks:
                mine[tok] += max(getattr(b, field).get(tok, 0)
                                 for b in branches)
        for b in branches:
            for tok, sites in b.sites.items():
                self.sites[tok].extend(
                    s for s in sites if s not in self.sites[tok])

    def reused(self) -> List[Tuple[object, int, int]]:
        out = []
        for tok in set(self.draws) | set(self.splits):
            d, s = self.draws.get(tok, 0), self.splits.get(tok, 0)
            if d >= 2 or s >= 2 or (d >= 1 and s >= 1):
                out.append((tok, d, s))
        return out


_PROPAGATE_1IN = frozenset({
    "squeeze", "reshape", "broadcast_in_dim", "transpose", "copy",
    "convert_element_type", "stop_gradient",
})


def _walk_keys(jaxpr, in_toks: List, use: _KeyUse) -> List:
    """Walk one (raw) jaxpr with `in_toks` bound to its invars; returns
    the tokens of its outvars. `use` accumulates consumptions across
    the whole nest."""
    env: Dict = {}

    def bind(var, tok):
        env[var] = tok

    def read(atom):
        # Literal atoms have no identity worth tracking; Vars not yet
        # bound (constvars, values produced by untracked prims) get a
        # stable fresh token on first sight
        if not hasattr(atom, "aval") or type(atom).__name__ == "Literal":
            return None
        if atom not in env:
            env[atom] = use.fresh("var")
        return env[atom]

    for var, tok in zip(jaxpr.invars, in_toks):
        bind(var, tok if tok is not None else use.fresh("in"))
    for var in jaxpr.constvars:
        bind(var, use.fresh("const"))

    def closed_parts(obj):
        """(raw_jaxpr) from a ClosedJaxpr or raw Jaxpr."""
        return obj.jaxpr if hasattr(obj, "consts") else obj

    for eqn in jaxpr.eqns:
        prim = eqn.primitive.name
        ins = [read(v) for v in eqn.invars]
        outs: List = [use.fresh("out") for _ in eqn.outvars]

        if prim in ("random_wrap", "random_unwrap"):
            outs[0] = ins[0]
        elif prim == "random_bits":
            use.consume(ins[0], "draw", prim)
        elif prim == "random_split":
            use.consume(ins[0], "split", prim)
        elif prim == "random_fold_in":
            pass                                    # fresh derivation
        elif prim in _PROPAGATE_1IN and len(ins) >= 1:
            outs[0] = ins[0]
        elif prim == "slice":
            outs[0] = ("slice", ins[0],
                       str(eqn.params.get("start_indices")),
                       str(eqn.params.get("limit_indices")))
        elif prim == "scan":
            body = closed_parts(eqn.params["jaxpr"])
            n_consts = eqn.params.get("num_consts", 0)
            n_carry = eqn.params.get("num_carry", 0)
            length = int(eqn.params.get("length", 1) or 1)
            const_toks = ins[:n_consts]
            carry_toks = ins[n_consts:n_consts + n_carry]
            xs_toks = [use.fresh("xs") for _ in ins[n_consts + n_carry:]]
            before = {t: (use.draws.get(t, 0), use.splits.get(t, 0))
                      for t in const_toks if t is not None}
            sub_out = _walk_keys(body, const_toks + carry_toks + xs_toks,
                                 use)
            if length > 1:
                # a key riding into the body as a loop CONSTANT is the
                # same key every iteration: one in-body consumption is
                # length consumptions
                for t, (d0, s0) in before.items():
                    if use.draws.get(t, 0) > d0:
                        use.consume(t, "draw", "scan-const")
                    if use.splits.get(t, 0) > s0:
                        use.consume(t, "split", "scan-const")
            # scan outs: [carry..., ys...]; carries may propagate a key
            outs = (list(sub_out[:n_carry])
                    + [use.fresh("ys") for _ in outs[n_carry:]])
        elif prim == "while":
            body = closed_parts(eqn.params["body_jaxpr"])
            cn = eqn.params.get("cond_nconsts", 0)
            bn = eqn.params.get("body_nconsts", 0)
            body_ins = ins[cn:cn + bn] + ins[cn + bn:]
            before = {t: (use.draws.get(t, 0), use.splits.get(t, 0))
                      for t in body_ins[:bn] if t is not None}
            _walk_keys(body, body_ins, use)
            # trip count unknown: assume >1 (the conservative read)
            for t, (d0, s0) in before.items():
                if use.draws.get(t, 0) > d0:
                    use.consume(t, "draw", "while-const")
                if use.splits.get(t, 0) > s0:
                    use.consume(t, "split", "while-const")
        elif prim == "cond":
            branches = eqn.params.get("branches", ())
            kids = []
            for br in branches:
                kid = _KeyUse()
                kid._fresh = use._fresh      # disjoint token ids
                _walk_keys(closed_parts(br), ins[1:], kid)
                kids.append(kid)
            if kids:
                use.merge_max(kids)
        else:
            descended = False
            for key in ("jaxpr", "call_jaxpr"):
                sub = eqn.params.get(key)
                if sub is not None and (hasattr(sub, "eqns")
                                        or hasattr(sub, "consts")):
                    raw = closed_parts(sub)
                    n = len(raw.invars)
                    sub_out = _walk_keys(raw, ins[:n], use)
                    outs = list(sub_out[:len(outs)]) \
                        + outs[len(sub_out):]
                    descended = True
                    break
            if not descended:
                # untracked primitive: outputs are fresh (identity lost
                # — e.g. manual uint32 arithmetic on a key defeats the
                # analyzer, by design: that code deserves review anyway)
                pass

        for var, tok in zip(eqn.outvars, outs):
            # a None token (literal-valued sub-output) must not alias
            # every other None — give it its own identity
            bind(var, tok if tok is not None else use.fresh("out"))

    return [read(v) for v in jaxpr.outvars]


@register
class RngReuseRule(GraphRule):
    """Detect PRNG key reuse in a traced program (see _KeyUse)."""

    id = "rng-key-reuse"
    doc = ("a PRNG key consumed by >=2 random draws/splits without an "
           "intervening split/fold_in in a traced hot program")

    def check(self, program: str, closed) -> Tuple[List[Finding], Dict]:
        use = _KeyUse()
        jaxpr = closed.jaxpr
        _walk_keys(jaxpr, [use.fresh("in") for _ in jaxpr.invars], use)
        findings = []
        for tok, d, s in sorted(use.reused(), key=str):
            sites = ",".join(use.sites.get(tok, [])[:6])
            findings.append(Finding(
                self.id, f"jaxpr:{program}", 0,
                f"PRNG key reused: {d} random draw(s) + {s} split(s) "
                f"of one key value (sites: {sites}) — derive fresh "
                f"keys with split/fold_in; reuse breaks the serving "
                f"layer's bit-identity and silently correlates noise"))
        return findings, {"keys_drawn": sum(use.draws.values()),
                          "keys_split": sum(use.splits.values()),
                          "reused": len(findings)}


# ---------------------------------------------------------------------------
# callback-leak
# ---------------------------------------------------------------------------

_CALLBACK_PRIMS = frozenset({"pure_callback", "io_callback",
                             "debug_callback"})


@register
class CallbackLeakRule(GraphRule):
    """No host callbacks inside jitted hot programs."""

    id = "callback-leak"
    doc = ("pure_callback/io_callback/debug_callback primitive inside "
           "a traced hot program — each dispatch is a host round-trip")

    def check(self, program: str, closed) -> Tuple[List[Finding], Dict]:
        findings: List[Finding] = []
        count = 0
        for eqn, mult in iter_eqns(closed.jaxpr):
            if eqn.primitive.name in _CALLBACK_PRIMS:
                count += mult
                findings.append(Finding(
                    self.id, f"jaxpr:{program}", 0,
                    f"`{eqn.primitive.name}` inside the jitted program "
                    f"(x{mult} per execution counting scan trips) — "
                    f"host work belongs outside the program, behind "
                    f"the module seams"))
        return findings, {"callbacks": count}


# ---------------------------------------------------------------------------
# bf16-upcast audit
# ---------------------------------------------------------------------------

@register
class UpcastAuditRule(GraphRule):
    """Budgeted bf16 -> f32 `convert_element_type` audit."""

    id = "bf16-upcast"
    doc = ("bf16->f32 upcast traffic in a traced hot program exceeds "
           "its budget (framework.UPCAST_BUDGET) — deliberate f32 "
           "accumulation is fine, silent growth is not")

    @staticmethod
    def _numel(aval) -> int:
        n = 1
        for s in aval.shape:
            n *= int(s)
        return n

    def check(self, program: str, closed) -> Tuple[List[Finding], Dict]:
        casts = elements = 0
        for eqn, mult in iter_eqns(closed.jaxpr):
            if eqn.primitive.name != "convert_element_type":
                continue
            src = getattr(eqn.invars[0], "aval", None)
            new = eqn.params.get("new_dtype")
            if src is None or new is None:
                continue
            if str(src.dtype) == "bfloat16" and str(new) == "float32":
                casts += mult
                elements += mult * self._numel(eqn.outvars[0].aval)
        budget = UPCAST_BUDGET.get(program, UPCAST_DEFAULT_BUDGET)
        findings: List[Finding] = []
        if elements > budget:
            findings.append(Finding(
                self.id, f"jaxpr:{program}", 0,
                f"bf16->f32 upcasts moved {elements} elements "
                f"({casts} casts) against a budget of {budget} — "
                f"raise the budget deliberately or drop the casts"))
        stats = {"casts": casts, "elements": elements}
        if program in UPCAST_BUDGET:
            stats["budget"] = budget
        return findings, stats

#!/bin/bash
# Patient tunnel prober: one long-timeout probe every ~15 min; on the
# first healthy answer, run the budget-bounded bench orchestrator and
# exit. Rationale in bench.py probe_backend: killed-mid-init clients
# leak a server-side lease for ~10-20 min, so sparse patient probes beat
# churn (r3 observed a 15-min-interval prober succeeding every time
# while 120s-retry probing failed for an hour). The orchestrator's
# --budget bounds the session so it cannot overrun into whatever owns
# the tunnel next (e.g. the round-end driver bench).
set -u
OUT=${1:-bench_session.out}
DEADLINE=$(( $(date +%s) + ${2:-10800} ))   # default: give up after 3 h
BUDGET=${3:-5400}

cd "$(dirname "$0")/.."

while [ "$(date +%s)" -lt "$DEADLINE" ]; do
  if timeout 560 python - <<'EOF'
import jax, sys
sys.exit(0 if jax.devices()[0].platform == "tpu" else 1)
EOF
  then
    echo "$(date -u +%FT%TZ) tunnel healthy; starting bench session" >&2
    exec python bench.py --budget "$BUDGET" --probe_timeout 90 \
        --probe_budget 120 --no_cpu_fallback >> "$OUT" 2>&1
  fi
  echo "$(date -u +%FT%TZ) tunnel still wedged; sleeping 900s" >&2
  sleep 900
done
echo "$(date -u +%FT%TZ) gave up waiting for the tunnel" >&2

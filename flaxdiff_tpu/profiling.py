"""Profiling and MFU accounting.

The reference has no profiling at all (reference trainer/simple_trainer.py
logs wall-clock epoch time only; no jax.profiler anywhere) — this module is
the TPU-native observability layer SURVEY §5.1 calls for: per-step FLOPs
from XLA's own cost model, model-FLOPs-utilization against the chip's peak,
and `jax.profiler` trace capture for xplane/perfetto inspection.

Usage:
    flops = compiled_flops(jitted_step, state, batch)   # per-device FLOPs
    meter = MFUMeter(flops_per_step=flops)
    with meter.step():                                  # times one step
        loss = step(...)
    meter.mfu()                                         # fraction of peak

    with trace("/tmp/trace"):                           # profiler capture
        run_steps()
"""
from __future__ import annotations

import contextlib
import time
from typing import Any, Optional

import jax

# Peak dense matmul throughput per chip, FLOP/s. bf16 (the MXU-native
# dtype this framework trains in). Public numbers from Google's TPU
# system documentation.
_PEAK_FLOPS_BF16 = {
    "TPU v2": 46e12,
    "TPU v3": 123e12,
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,   # v5e
    "TPU v5e": 197e12,
    "TPU v5": 459e12,        # v5p (kind string "TPU v5")
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,   # v6e / Trillium
    "TPU v6e": 918e12,
    "TPU7x": 2307e12,
}


def device_peak_flops(device: Optional[Any] = None) -> Optional[float]:
    """Peak bf16 FLOP/s of `device` (default: first local device).

    Returns None on hosts where the peak is unknown (e.g. CPU test
    meshes) — MFU is then unreportable rather than wrong."""
    if device is None:
        device = jax.local_devices()[0]
    kind = getattr(device, "device_kind", "")
    if kind in _PEAK_FLOPS_BF16:
        return _PEAK_FLOPS_BF16[kind]
    # longest-prefix fallback ("TPU v5 lite chip" style variants)
    best = None
    for name, flops in _PEAK_FLOPS_BF16.items():
        if kind.startswith(name) and (best is None or len(name) > best[0]):
            best = (len(name), flops)
    return best[1] if best else None


def compiled_flops(jitted_fn, *args, **kwargs) -> Optional[float]:
    """Per-device FLOPs of one execution of `jitted_fn(*args, **kwargs)`.

    Uses XLA's cost analysis on the compiled executable — the same numbers
    the compiler schedules against, so rematerialization (jax.checkpoint)
    and fusion decisions are included, unlike hand-derived analytic counts.
    Under SPMD jit the executable is the per-device program, so the figure
    is already per-chip. Returns None if the backend exposes no analysis.
    """
    try:
        compiled = jitted_fn.lower(*args, **kwargs).compile()
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):  # older jax returned [dict]
            cost = cost[0] if cost else {}
        flops = cost.get("flops")
        return float(flops) if flops and flops > 0 else None
    except Exception:
        return None


def _dot_general_flops(eqn) -> float:
    lhs = eqn.invars[0].aval
    rhs = eqn.invars[1].aval
    (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
    batch = 1.0
    for i in lb:
        batch *= lhs.shape[i]
    contract = 1.0
    for i in lc:
        contract *= lhs.shape[i]
    m = 1.0
    for i in range(len(lhs.shape)):
        if i not in lc and i not in lb:
            m *= lhs.shape[i]
    n = 1.0
    for i in range(len(rhs.shape)):
        if i not in rc and i not in rb:
            n *= rhs.shape[i]
    return 2.0 * batch * m * n * contract


def _conv_flops(eqn) -> float:
    out = eqn.outvars[0].aval
    rhs = eqn.invars[1].aval
    dn = eqn.params["dimension_numbers"]
    n_out = 1.0
    for s in out.shape:
        n_out *= s
    # kernel: rhs_spec = (out_ch_dim, in_ch_dim, *spatial_dims)
    in_ch_per_group = rhs.shape[dn.rhs_spec[1]]
    k_spatial = 1.0
    for i in dn.rhs_spec[2:]:
        k_spatial *= rhs.shape[i]
    return 2.0 * n_out * in_ch_per_group * k_spatial


def _iter_subjaxprs(params):
    """Yield every (closed)jaxpr nested in an eqn's params."""
    for v in params.values():
        vs = v if isinstance(v, (tuple, list)) else (v,)
        for x in vs:
            if hasattr(x, "jaxpr") and hasattr(x, "consts"):   # ClosedJaxpr
                yield x.jaxpr
            elif hasattr(x, "eqns"):                            # raw Jaxpr
                yield x


def jaxpr_flops(jaxpr) -> float:
    """Matmul+conv FLOPs of a jaxpr with TRUE (unpadded) shapes.

    The analytic "model FLOPs" counter VERDICT r2 weak #2 calls for:
    `compiled_flops` reads XLA's cost analysis of the program that actually
    runs, which includes padding work (e.g. the flash path's head_dim
    64->128 lane pad) and rematerialized recompute — honest about the
    hardware, inflated as a *model* FLOPs numerator. This walks the traced
    jaxpr instead, counting only dot_general / conv_general_dilated at
    their traced shapes (the standard model-FLOPs convention: elementwise
    and softmax work excluded). Trace the step with the "xla" attention
    backend so attention isn't hidden inside an opaque pallas_call.

    Recurses into nested jaxprs (pjit, custom_vjp, remat); scan bodies are
    multiplied by trip count; cond counts the most expensive branch.
    """
    total = 0.0
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name == "dot_general":
            total += _dot_general_flops(eqn)
        elif name == "conv_general_dilated":
            total += _conv_flops(eqn)
        elif name == "cond":
            branches = eqn.params.get("branches", ())
            total += max((jaxpr_flops(b.jaxpr) for b in branches),
                         default=0.0)
        else:
            mult = eqn.params.get("length", 1) if name == "scan" else 1
            for sub in _iter_subjaxprs(eqn.params):
                total += mult * jaxpr_flops(sub)
    return total


def traced_model_flops(fn, *args, **kwargs) -> Optional[float]:
    """`jaxpr_flops` of `fn(*args, **kwargs)` (abstract trace, no device).

    Per-call FLOPs at true shapes. NOTE: pallas_call bodies are opaque to
    tracing — call this on a variant of the program whose attention uses
    the "xla" backend to get the full model count."""
    try:
        closed = jax.make_jaxpr(fn)(*args, **kwargs)
        return jaxpr_flops(closed.jaxpr)
    except Exception:
        return None


def mfu(flops_per_step: float, step_time_s: float,
        peak_flops: Optional[float] = None) -> Optional[float]:
    """Model FLOPs utilization: achieved FLOP/s over peak FLOP/s."""
    if peak_flops is None:
        peak_flops = device_peak_flops()
    if not peak_flops or step_time_s <= 0:
        return None
    return flops_per_step / step_time_s / peak_flops


class MFUMeter:
    """Accumulates step timings and reports throughput + MFU.

    `flops_per_step` is per-device FLOPs (from `compiled_flops`); timings
    are wall-clock per step. Call `.observe(dt)` or use `.step()` as a
    context manager around one synchronous step."""

    def __init__(self, flops_per_step: Optional[float] = None,
                 peak_flops: Optional[float] = None):
        self.flops_per_step = flops_per_step
        self.peak_flops = peak_flops if peak_flops is not None \
            else device_peak_flops()
        self.total_time = 0.0
        self.steps = 0

    def observe(self, dt: float, steps: int = 1):
        self.total_time += dt
        self.steps += steps

    @contextlib.contextmanager
    def step(self):
        t0 = time.perf_counter()
        yield
        self.observe(time.perf_counter() - t0)

    def mean_step_time(self) -> Optional[float]:
        return self.total_time / self.steps if self.steps else None

    def mfu(self) -> Optional[float]:
        dt = self.mean_step_time()
        if dt is None or self.flops_per_step is None:
            return None
        return mfu(self.flops_per_step, dt, self.peak_flops)

    def achieved_tflops(self) -> Optional[float]:
        dt = self.mean_step_time()
        if dt is None or self.flops_per_step is None:
            return None
        return self.flops_per_step / dt / 1e12

    def reset(self):
        self.total_time = 0.0
        self.steps = 0


@contextlib.contextmanager
def trace(logdir: str, host_tracer_level: int = 2):
    """jax.profiler capture around a block; view with xprof/tensorboard
    or perfetto. Degrades to a no-op context if the profiler cannot
    start (e.g. a second concurrent trace) — but records a
    `trace_failed` resilience event either way, because "the profile I
    asked for silently doesn't exist" is undiagnosable after the run
    (the pre-telemetry bare `except: pass` here was exactly that)."""
    started = False
    try:
        jax.profiler.start_trace(logdir)
        started = True
    except Exception as e:  # noqa: BLE001 — degrade, but visibly
        from .resilience.events import record_event
        record_event("trace_failed", "profiler.start_trace",
                     detail=f"{type(e).__name__}: {e} (logdir={logdir})")
    try:
        yield
    finally:
        if started:
            try:
                jax.profiler.stop_trace()
            except Exception as e:  # noqa: BLE001 — degrade, but visibly
                from .resilience.events import record_event
                record_event("trace_failed", "profiler.stop_trace",
                             detail=f"{type(e).__name__}: {e} "
                                    f"(logdir={logdir})")


@contextlib.contextmanager
def annotate(name: str):
    """Named TraceAnnotation visible in profiler timelines."""
    with jax.profiler.TraceAnnotation(name):
        yield

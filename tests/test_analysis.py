"""Graph-hygiene analyzer (ISSUE 9): one true-positive fixture per
rule, clean-pass assertions on the REAL hot programs, allowlist budget
semantics, and the custom-root CLI mode.

The full-repo acceptance run (every AST rule + every jaxpr analyzer
over the production tree, exit 0) lives in tests/test_tools.py as the
one unified-CLI invocation; this file proves each rule actually
DETECTS what it claims to detect — a gate that never fires is worse
than no gate, it's false confidence.
"""
import ast
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flaxdiff_tpu.analysis import framework
from flaxdiff_tpu.analysis import ast_rules as AR  # registers AST rules
from flaxdiff_tpu.analysis import graph_rules as GR  # registers graph
from flaxdiff_tpu.analysis.framework import (ALLOWLIST, AST_RULES,
                                             GRAPH_RULES, Finding,
                                             apply_budgets)


def _check(rule_id, src, relpath="fixture.py"):
    rule = AST_RULES[rule_id]
    return rule.check(relpath, ast.parse(src), src)


# -- host-sync ----------------------------------------------------------------

def test_host_sync_flags_every_sync_form():
    src = (
        "import jax\n"
        "import numpy as np\n"
        "import jax.numpy as jnp\n"
        "def hot(x, arrs):\n"
        "    a = x.item()\n"                    # 5
        "    jax.block_until_ready(x)\n"        # 6
        "    b = jax.device_get(x)\n"           # 7
        "    c = np.asarray(x)\n"               # 8
        "    d = float(jnp.std(x))\n"           # 9
        "    return a, b, c, d\n")
    hits = _check("host-sync", src)
    assert sorted(f.line for f in hits) == [5, 6, 7, 8, 9]


def test_host_sync_blesses_the_seams_and_h2d():
    """Syncs INSIDE the module seams are the contract, not a finding;
    jnp.asarray is H2D upload, not a host sync."""
    src = (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "def _fetch_losses(arrs):\n"
        "    return [float(v) for v in jax.device_get(list(arrs))]\n"
        "def _block_until_ready(x):\n"
        "    jax.block_until_ready(x)\n"
        "def upload(x):\n"
        "    return jnp.asarray(x)\n"           # H2D: fine
        "def cfg(v):\n"
        "    return float(v)\n")                # plain cast: fine
    assert _check("host-sync", src) == []


def test_host_sync_scoping():
    """Repo mode: only trainer/serving/samplers files are in scope."""
    rule = AST_RULES["host-sync"]
    assert rule.applies("flaxdiff_tpu/trainer/trainer.py")
    assert rule.applies("flaxdiff_tpu/serving/scheduler.py")
    assert not rule.applies("flaxdiff_tpu/telemetry/metrics.py")
    assert not rule.applies("scripts/diagnose_run.py")
    assert rule.applies("anything.py", scoped=False)


# -- pallas-lane-slice --------------------------------------------------------

def test_lane_slice_flags_bounded_last_axis():
    src = (
        "def _bad_kernel(x_ref, o_ref):\n"
        "    o_ref[:, :64] = x_ref[:, :64]\n"       # both sides flagged
        "def also_bad(q_ref, o_ref):\n"
        "    o_ref[..., 0:8] = q_ref[..., 0:8] * 2\n")
    hits = _check("pallas-lane-slice", src)
    assert len(hits) == 4
    assert all(f.line in (2, 4) for f in hits)


def test_lane_slice_accepts_kernel_idioms():
    """The repo's actual kernel patterns — block reads, full-width
    stores, python-tuple slicing of the refs vararg — all pass; and a
    NON-kernel function may slice freely."""
    src = (
        "def _good_kernel(x_ref, s_ref, o_ref):\n"
        "    x = x_ref[0]\n"
        "    o_ref[...] = x\n"
        "    o_ref[0, 0] = x.sum()\n"
        "def _unpack_kernel(*refs, nviews):\n"
        "    x_ref = refs[0]\n"
        "    s_refs = refs[1:1 + 2 * nviews:2]\n"    # tuple slice: fine
        "    x_ref[0] = x_ref[0] * 2\n"
        "def host_helper(arr):\n"
        "    return arr[:, :64]\n")                  # not a kernel
    assert _check("pallas-lane-slice", src) == []


# -- silent-except (ported from the standalone gate's tests) ------------------

def test_silent_except_flags_new_offender():
    src = (
        "def f():\n"
        "    try:\n"
        "        risky()\n"
        "    except Exception:\n"
        "        pass\n"
        "    try:\n"
        "        risky()\n"
        "    except (ValueError, BaseException):\n"
        "        ...\n")
    hits = _check("silent-except", src)
    assert sorted(f.line for f in hits) == [4, 8]


def test_silent_except_accepts_handlers_that_act():
    src = (
        "def f():\n"
        "    try:\n"
        "        risky()\n"
        "    except Exception as e:\n"
        "        record_event('x', 'y', detail=repr(e))\n"
        "    try:\n"
        "        risky()\n"
        "    except ValueError:\n"      # narrow catch: allowed silent
        "        pass\n"
        "    try:\n"
        "        risky()\n"
        "    except Exception:\n"
        "        raise RuntimeError('context')\n")
    assert _check("silent-except", src) == []


def test_bare_except_allowlist_is_empty():
    """Satellite: the four grandfathered sites were fixed — the budget
    must STAY empty (re-adding debt here is a review event)."""
    assert ALLOWLIST["silent-except"] == {}


# -- metric-name --------------------------------------------------------------

def test_metric_name_wildcards_and_fstrings(tmp_path):
    code = (
        "def f(reg, name):\n"
        "    reg.histogram(f'phase/{name}').observe(0.1)\n"
        "    reg.gauge('numerics/module/Conv_0/grad_norm').set(1.0)\n"
        "    reg.gauge(name).set(1.0)\n")          # variable: ungated
    docs = tmp_path / "docs.md"
    rule = AST_RULES["metric-name"]
    old = rule.docs_path
    try:
        docs.write_text("- `phase/<name>` histograms\n"
                        "- `numerics/module/<module>/<stat>` rows\n")
        rule.docs_path = str(docs)
        assert _check("metric-name", code) == []
        # remove the wildcard: the f-string prefix is now undocumented
        docs.write_text("- `numerics/module/<module>/<stat>` rows\n")
        hits = _check("metric-name", code)
        assert len(hits) == 1 and "phase/" in hits[0].message
    finally:
        rule.docs_path = old


# -- rng-key-reuse ------------------------------------------------------------

def _rng_check(fn, *args):
    closed = jax.make_jaxpr(fn)(*args)
    findings, stats = GRAPH_RULES["rng-key-reuse"].check("fix", closed)
    return findings, stats


def test_rng_reuse_double_draw_detected():
    def f(key):
        return (jax.random.normal(key, (2,))
                + jax.random.normal(key, (2,)))     # REUSE

    findings, stats = _rng_check(f, jax.random.PRNGKey(0))
    assert len(findings) == 1
    assert "reused" in findings[0].message
    assert stats["keys_drawn"] == 2


def test_rng_reuse_draw_after_split_detected():
    def f(key):
        k1, _ = jax.random.split(key)
        return jax.random.normal(key, (2,))         # key already split

    findings, _ = _rng_check(f, jax.random.PRNGKey(0))
    assert len(findings) == 1


def test_rng_double_split_detected():
    def f(key):
        a = jax.random.split(key)                   # same children
        b = jax.random.split(key)                   # twice
        return jax.random.normal(a[0], ()) + jax.random.normal(b[1], ())

    findings, _ = _rng_check(f, jax.random.PRNGKey(0))
    assert len(findings) == 1


def test_rng_clean_split_lineage_passes():
    """The framework's own derivation patterns: fold_in + split + one
    draw per child, a carried key split each scan step (the chunk
    program's pattern) — zero findings."""
    def f(key, step):
        key = jax.random.fold_in(key, step)
        k1, k2, k3 = jax.random.split(key, 3)
        x = jax.random.normal(k1, (2,))
        y = jax.random.bernoulli(k2, 0.5, (2,))

        def body(carry, _):
            rng, acc = carry
            rng, sub = jax.random.split(rng)
            return (rng, acc + jax.random.normal(sub, (2,))), ()

        (rng, acc), _ = jax.lax.scan(body, (k3, x), None, length=4)
        return acc + y

    findings, stats = _rng_check(f, jax.random.PRNGKey(0),
                                 jnp.zeros((), jnp.int32))
    assert findings == []
    assert stats["keys_drawn"] >= 2


def test_rng_scan_constant_key_detected():
    """A key riding into a scan body as a loop CONSTANT draws the same
    bits every iteration — the classic 'it compiled and the loss even
    went down' key bug."""
    def f(key):
        def body(acc, _):
            return acc + jax.random.normal(key, (2,)), ()   # closed over!

        acc, _ = jax.lax.scan(body, jnp.zeros((2,)), None, length=4)
        return acc

    findings, _ = _rng_check(f, jax.random.PRNGKey(0))
    assert len(findings) == 1
    assert "scan-const" in findings[0].message


# -- callback-leak ------------------------------------------------------------

def test_callback_leak_detected_and_clean():
    def leaky(x):
        return jax.pure_callback(
            lambda v: np.asarray(v) * 2, jax.ShapeDtypeStruct(
                (2,), jnp.float32), x)

    closed = jax.make_jaxpr(leaky)(jnp.ones((2,)))
    findings, stats = GRAPH_RULES["callback-leak"].check("fix", closed)
    assert len(findings) == 1 and stats["callbacks"] == 1

    def clean(x):
        return x * 2

    closed = jax.make_jaxpr(clean)(jnp.ones((2,)))
    findings, stats = GRAPH_RULES["callback-leak"].check("fix", closed)
    assert findings == [] and stats["callbacks"] == 0


def test_debug_print_is_a_callback_leak():
    def leaky(x):
        jax.debug.print("x={x}", x=x)
        return x * 2

    closed = jax.make_jaxpr(leaky)(jnp.ones((2,)))
    findings, _ = GRAPH_RULES["callback-leak"].check("fix", closed)
    assert len(findings) == 1


# -- bf16-upcast --------------------------------------------------------------

def test_upcast_audit_counts_and_budgets(monkeypatch):
    def f(x):
        return x.astype(jnp.float32) * 2.0

    closed = jax.make_jaxpr(f)(jnp.ones((4, 8), jnp.bfloat16))
    rule = GRAPH_RULES["bf16-upcast"]
    findings, stats = rule.check("fix", closed)
    assert stats == {"casts": 1, "elements": 32}
    assert findings == []       # default budget: report-only
    monkeypatch.setitem(framework.UPCAST_BUDGET, "fix", 16)
    findings, stats = rule.check("fix", closed)
    assert len(findings) == 1 and "budget of 16" in findings[0].message
    assert stats["budget"] == 16


# -- the real hot programs (tier-1 clean pass) --------------------------------

@pytest.mark.parametrize("name", [
    "train_step", "train_step_monitored", "chunk_ddim",
    "chunk_euler_ancestral", "solo_ddim"])
def test_real_programs_pass_rng_and_callback_rules(name):
    """ISSUE 9 acceptance: zero RNG-reuse and callback findings on the
    REAL train-step and sampler programs — the invariants PR 5/8 hand-
    enforced, now mechanically checked against the live code."""
    from flaxdiff_tpu.analysis.programs import hot_programs
    [(prog_name, closed)] = hot_programs([name])
    for rid in ("rng-key-reuse", "callback-leak"):
        findings, _ = GRAPH_RULES[rid].check(prog_name, closed)
        assert findings == [], (rid, [f.message for f in findings])


def test_hot_program_inventory_traces():
    from flaxdiff_tpu.analysis.programs import (PROGRAM_BUILDERS,
                                                hot_programs)
    progs = hot_programs()
    assert [n for n, _ in progs] == sorted(PROGRAM_BUILDERS)
    assert all(hasattr(c, "jaxpr") for _, c in progs)
    with pytest.raises(ValueError, match="unknown program"):
        hot_programs(["nope"])


def test_bf16_step_upcast_within_budget():
    """The audit's real subject: the bf16-policy train step's upcast
    traffic stays within its pinned budget (growth = a new cast crept
    into the step code — raise the budget deliberately or remove it)."""
    from flaxdiff_tpu.analysis.programs import hot_programs
    [(name, closed)] = hot_programs(["train_step_bf16"])
    findings, stats = GRAPH_RULES["bf16-upcast"].check(name, closed)
    assert findings == []
    assert 0 < stats["elements"] <= framework.UPCAST_BUDGET[name]


# -- budgets + report ---------------------------------------------------------

def test_budget_semantics_over_under_and_slack():
    f1 = Finding("r", "a.py", 1, "x")
    f2 = Finding("r", "a.py", 2, "y")
    # over budget: every finding in the file fails, budget in message
    fails, notes = apply_budgets([f1, f2], {"r": {"a.py": 1}})
    assert len(fails) == 2 and "budget 1" in fails[0].message
    # at budget: pass, no note
    fails, notes = apply_budgets([f1, f2], {"r": {"a.py": 2}})
    assert fails == [] and notes == []
    # under budget: pass + shrink note
    fails, notes = apply_budgets([f1], {"r": {"a.py": 2}})
    assert fails == [] and len(notes) == 1 and "shrink" in notes[0]
    # stale budget (no findings at all): shrink note too
    fails, notes = apply_budgets([], {"r": {"gone.py": 3}})
    assert fails == [] and len(notes) == 1 and "gone.py" in notes[0]


def test_custom_root_mode_scans_with_empty_allowlist(tmp_path):
    bad = tmp_path / "offender.py"
    bad.write_text("try:\n"
                   "    risky()\n"
                   "except Exception:\n"
                   "    pass\n")
    report = framework.run(rule_ids=["silent-except"],
                           root=str(tmp_path), with_graph=False)
    assert not report.ok
    assert report.failures[0].file == "offender.py"
    assert report.failures[0].line == 3


def test_unknown_rule_id_rejected():
    with pytest.raises(SystemExit, match="unknown rule"):
        framework.run(rule_ids=["no-such-rule"], with_graph=False)


def test_report_json_shape():
    """The machine contract: version, ok, sorted findings with
    over_budget flags, notes, graph stats — and no absolute paths."""
    report = framework.run(rule_ids=["silent-except"], with_graph=False)
    blob = framework.stable_json(report)
    data = json.loads(blob)
    assert data["version"] == 1 and data["ok"] is True
    assert set(data) == {"version", "ok", "rules", "findings",
                         "notes", "graph"}
    assert "silent-except" in data["rules"]
    assert "/root/" not in blob

"""Fused GroupNorm + SiLU Pallas kernels (resblock prologue).

The reference runs GroupNorm and SiLU as separate XLA ops
(reference flaxdiff/models/common.py:283-334); on TPU the chain is
HBM-bandwidth bound, so the affine + activation are fused into the
normalization pass. Two tiled kernels (stats, then normalize) so samples
of any spatial size stream through VMEM in blocks:

- stats kernel: per (sample, hw-block) partial group sums/sumsqs, computed
  with 2D matmuls against a [C, G] membership mask (Mosaic can't reshape
  across the lane dim, and the mask matmul rides the MXU).
- normalize kernel: (x - mean) * rstd * scale + bias (+ SiLU) per block.

Backward (r5): dedicated Pallas kernels reusing the forward's saved
per-group stats — one stats pass over (x, g) producing the dx correction
terms and dscale/dbias partials, an O(B*G + C) XLA finalize, then the dx
pass (FLAXDIFF_FUSED_NORM_BWD=xla restores the recompute-through-XLA
backward for A/B). Falls back to XLA off-TPU.
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# Target f32 bytes for one [block_hw, C] input block in VMEM. The kernels
# keep ~3 block-sized f32 temporaries live, so 1 MiB blocks stay well
# under the ~16 MiB scoped-VMEM limit.
_BLOCK_BYTES = 1 << 20


def _block_hw(hw: int, c: int) -> int:
    rows = max(8, _BLOCK_BYTES // (4 * c))
    rows = min(rows, hw)
    # Round to a sublane-friendly multiple of 8.
    return max(8, (rows // 8) * 8)


def _fused_norm_interpret() -> bool:
    """FLAXDIFF_FUSED_NORM=interpret mirrors FLAXDIFF_FLASH_INTERPRET
    (ops/attention.py _flash_interpret): run the real Pallas kernels —
    fwd AND the r5 backward — through the interpreter inside full
    models on CPU. One helper so fwd and bwd cannot read the env
    differently (interpreted fwd + Mosaic bwd would crash)."""
    return os.environ.get("FLAXDIFF_FUSED_NORM") == "interpret"


def _member_mask(c: int, groups: int) -> jnp.ndarray:
    cg = c // groups
    ch = jax.lax.broadcasted_iota(jnp.int32, (c, groups), 0)
    gi = jax.lax.broadcasted_iota(jnp.int32, (c, groups), 1)
    return (ch // cg == gi).astype(jnp.float32)


def _gn_stats_kernel(x_ref, o_ref, *, groups: int, hw: int, block_hw: int):
    i = pl.program_id(1)
    x = x_ref[0].astype(jnp.float32)  # [block_hw, C]
    c = x.shape[1]
    valid = (i * block_hw
             + jax.lax.broadcasted_iota(jnp.int32, x.shape, 0)) < hw
    x = jnp.where(valid, x, 0.0)
    member = _member_mask(c, groups)
    # HIGHEST precision: tiny [1,C]x[C,G] matmuls, but bf16 MXU rounding
    # here would corrupt the statistics.
    dot = functools.partial(jax.lax.dot_general,
                            preferred_element_type=jnp.float32,
                            precision=jax.lax.Precision.HIGHEST)
    colsum = jnp.sum(x, axis=0, keepdims=True)            # [1, C]
    gsum = dot(colsum, member, (((1,), (0,)), ((), ())))  # [1, G]
    # Shifted second moment: accumulate sum((x - block_mean)^2) instead of
    # sum(x^2), so large-mean activations don't cancel catastrophically in
    # the E[x^2]-E[x]^2 finalize (blocks are Welford-merged there).
    nb = jnp.minimum(block_hw, hw - i * block_hw).astype(jnp.float32)
    mean_g = gsum / (nb * (c // groups))                   # [1, G]
    mean_c = dot(mean_g, member, (((1,), (1,)), ((), ()))) # [1, C]
    xc = jnp.where(valid, x - mean_c, 0.0)
    colsq = jnp.sum(xc * xc, axis=0, keepdims=True)        # [1, C]
    gsq = dot(colsq, member, (((1,), (0,)), ((), ())))     # [1, G]
    o_ref[0, 0] = jnp.concatenate([gsum, gsq], axis=0)     # [2, G]


def _gn_norm_kernel(x_ref, mean_ref, rstd_ref, scale_ref, bias_ref, o_ref, *,
                    apply_silu: bool):
    x = x_ref[0].astype(jnp.float32)  # [block_hw, C]
    out = (x - mean_ref[0].astype(jnp.float32)) \
        * rstd_ref[0].astype(jnp.float32)
    out = out * scale_ref[...].astype(jnp.float32) \
        + bias_ref[...].astype(jnp.float32)
    if apply_silu:
        out = out * jax.nn.sigmoid(out)
    o_ref[0] = out.astype(o_ref.dtype)


def _bwd_dy(x, g, mean, rstd, scale, bias, apply_silu: bool):
    """(xhat, dy, dxhat) from loaded f32 blocks — the ONE copy of the
    normalize + SiLU-derivative recompute shared by both backward
    kernels (they must stay byte-identical or the stats pass and the
    dx pass silently disagree)."""
    xhat = (x - mean) * rstd
    if apply_silu:
        y = xhat * scale + bias
        sig = jax.nn.sigmoid(y)
        dy = g * sig * (1.0 + y * (1.0 - sig))
    else:
        dy = g
    return xhat, dy, dy * scale


def _gn_bwd_stats_kernel(x_ref, g_ref, mean_ref, rstd_ref, scale_ref,
                         bias_ref, gsums_ref, csums_ref, *,
                         groups: int, hw: int, block_hw: int,
                         apply_silu: bool):
    """Per-(sample, hw-block) backward partials in one read of (x, g):
    group sums of (dxhat, dxhat*xhat) for the dx correction terms and
    per-channel sums of (dy, dy*xhat) for dbias/dscale."""
    i = pl.program_id(1)
    x = x_ref[0].astype(jnp.float32)             # [block_hw, C]
    g = g_ref[0].astype(jnp.float32)
    c = x.shape[1]
    valid = (i * block_hw
             + jax.lax.broadcasted_iota(jnp.int32, x.shape, 0)) < hw
    x = jnp.where(valid, x, 0.0)
    g = jnp.where(valid, g, 0.0)

    mean = mean_ref[0].astype(jnp.float32)       # [1, C]
    rstd = rstd_ref[0].astype(jnp.float32)
    scale = scale_ref[...].astype(jnp.float32)
    bias = bias_ref[...].astype(jnp.float32)

    xhat, dy, dxhat = _bwd_dy(x, g, mean, rstd, scale, bias, apply_silu)

    dot = functools.partial(jax.lax.dot_general,
                            preferred_element_type=jnp.float32,
                            precision=jax.lax.Precision.HIGHEST)
    member = _member_mask(c, groups)
    s1_c = jnp.sum(dxhat, axis=0, keepdims=True)           # [1, C]
    s2_c = jnp.sum(dxhat * xhat, axis=0, keepdims=True)    # [1, C]
    gsums_ref[0, 0] = jnp.concatenate(
        [dot(s1_c, member, (((1,), (0,)), ((), ()))),
         dot(s2_c, member, (((1,), (0,)), ((), ())))], axis=0)   # [2, G]
    csums_ref[0, 0] = jnp.concatenate(
        [jnp.sum(dy, axis=0, keepdims=True),
         jnp.sum(dy * xhat, axis=0, keepdims=True)], axis=0)     # [2, C]


def _gn_bwd_dx_kernel(x_ref, g_ref, mean_ref, rstd_ref, scale_ref,
                      bias_ref, s1_ref, s2_ref, dx_ref, *,
                      apply_silu: bool):
    """dx = rstd * (dxhat - mean_S(dxhat) - xhat * mean_S(dxhat*xhat))
    per block; the mean_S terms arrive per-channel-broadcast from the
    XLA finalize."""
    x = x_ref[0].astype(jnp.float32)
    g = g_ref[0].astype(jnp.float32)
    mean = mean_ref[0].astype(jnp.float32)
    rstd = rstd_ref[0].astype(jnp.float32)
    scale = scale_ref[...].astype(jnp.float32)
    bias = bias_ref[...].astype(jnp.float32)

    xhat, _dy, dxhat = _bwd_dy(x, g, mean, rstd, scale, bias, apply_silu)
    dx = rstd * (dxhat - s1_ref[0].astype(jnp.float32)
                 - xhat * s2_ref[0].astype(jnp.float32))
    dx_ref[0] = dx.astype(dx_ref.dtype)


def _pallas_gn_silu_bwd(x, scale, bias, mean_c, rstd_c, g, groups,
                        apply_silu, interpret):
    """Dedicated Pallas backward (VERDICT r4 #3): two tiled passes over
    (x, g) — partial sums, XLA finalize (O(B*G + C)), then dx — instead
    of re-running the whole forward chain through XLA autodiff. Returns
    (dx, dscale, dbias)."""
    orig_shape = x.shape
    b, c = x.shape[0], x.shape[-1]
    xr = x.reshape(b, -1, c)
    gr = g.reshape(b, -1, c)
    hw = xr.shape[1]
    # half the forward's block rows: these kernels stream TWO block-size
    # inputs (x and g) plus the xhat/y/sigmoid/dy temporaries, so the
    # forward's sizing would roughly double live VMEM
    blk = max(8, (_block_hw(hw, c) // 2) // 8 * 8)
    blk = min(blk, max(8, (hw // 8) * 8)) if hw >= 8 else 8
    nblk = pl.cdiv(hw, blk)
    cg = c // groups

    gsums, csums = pl.pallas_call(
        functools.partial(_gn_bwd_stats_kernel, groups=groups, hw=hw,
                          block_hw=blk, apply_silu=apply_silu),
        grid=(b, nblk),
        in_specs=[
            pl.BlockSpec((1, blk, c), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, blk, c), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, 1, c), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, 1, c), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, c), lambda i, j: (0, 0)),
            pl.BlockSpec((1, c), lambda i, j: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, 2, groups), lambda i, j: (i, j, 0, 0)),
            pl.BlockSpec((1, 1, 2, c), lambda i, j: (i, j, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, nblk, 2, groups), jnp.float32),
            jax.ShapeDtypeStruct((b, nblk, 2, c), jnp.float32),
        ],
        interpret=interpret,
    )(xr, gr, mean_c, rstd_c, scale.reshape(1, c), bias.reshape(1, c))

    # XLA finalize: merge blocks, normalize the group means, broadcast
    # back to per-channel [B, 1, C] for the dx pass.
    n = float(hw * cg)
    s1_g = jnp.sum(gsums[:, :, 0], axis=1) / n        # [B, G]
    s2_g = jnp.sum(gsums[:, :, 1], axis=1) / n
    s1_c = jnp.repeat(s1_g, cg, axis=-1)[:, None, :]  # [B, 1, C]
    s2_c = jnp.repeat(s2_g, cg, axis=-1)[:, None, :]
    dbias = jnp.sum(csums[:, :, 0], axis=(0, 1)).astype(bias.dtype)
    dscale = jnp.sum(csums[:, :, 1], axis=(0, 1)).astype(scale.dtype)

    dx = pl.pallas_call(
        functools.partial(_gn_bwd_dx_kernel, apply_silu=apply_silu),
        grid=(b, nblk),
        in_specs=[
            pl.BlockSpec((1, blk, c), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, blk, c), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, 1, c), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, 1, c), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, c), lambda i, j: (0, 0)),
            pl.BlockSpec((1, c), lambda i, j: (0, 0)),
            pl.BlockSpec((1, 1, c), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, 1, c), lambda i, j: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, blk, c), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((b, hw, c), x.dtype),
        interpret=interpret,
    )(xr, gr, mean_c, rstd_c, scale.reshape(1, c), bias.reshape(1, c),
      s1_c, s2_c)
    return dx.reshape(orig_shape), dscale, dbias


def _xla_groupnorm_silu(x, scale, bias, groups, eps, apply_silu):
    b = x.shape[0]
    c = x.shape[-1]
    xf = x.astype(jnp.float32).reshape(b, -1, groups, c // groups)
    mean = jnp.mean(xf, axis=(1, 3), keepdims=True)
    var = jnp.mean((xf - mean) ** 2, axis=(1, 3), keepdims=True)
    xn = ((xf - mean) * jax.lax.rsqrt(var + eps)).reshape(x.shape)
    out = xn * scale + bias
    if apply_silu:
        out = jax.nn.silu(out)
    return out.astype(x.dtype)


def _impl_stats(x: jax.Array, scale: jax.Array, bias: jax.Array,
                groups: int, eps: float, apply_silu: bool,
                interpret: bool, force_pallas: bool):
    """(out, mean_c, rstd_c) — stats are None on the XLA fallback paths
    (their backward recomputes through XLA autodiff; the Pallas
    backward needs the saved stats)."""
    c = x.shape[-1]
    assert c % groups == 0, f"channels {c} not divisible by groups {groups}"
    orig_shape = x.shape
    b = x.shape[0]

    if _fused_norm_interpret():
        interpret = True
    on_tpu = jax.devices()[0].platform == "tpu"
    if not force_pallas and not (on_tpu or interpret):
        return (_xla_groupnorm_silu(x, scale, bias, groups, eps,
                                    apply_silu), None, None)
    if not force_pallas and os.environ.get("FLAXDIFF_FUSED_NORM") == "xla":
        # A/B escape hatch: the r3 trace showed ~750 layout copies/step
        # around the pallas custom calls — the bench's ablate stage uses
        # this to measure whether the fused kernel pays for its copies
        # in-context on real hardware
        return (_xla_groupnorm_silu(x, scale, bias, groups, eps,
                                    apply_silu), None, None)

    xr = x.reshape(b, -1, c)
    hw = xr.shape[1]
    blk = _block_hw(hw, c)
    nblk = pl.cdiv(hw, blk)

    # Pass 1: per-block partial group sums -> [B, nblk, 2, G].
    sums = pl.pallas_call(
        functools.partial(_gn_stats_kernel, groups=groups, hw=hw,
                          block_hw=blk),
        grid=(b, nblk),
        in_specs=[pl.BlockSpec((1, blk, c), lambda i, j: (i, j, 0))],
        out_specs=pl.BlockSpec((1, 1, 2, groups), lambda i, j: (i, j, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((b, nblk, 2, groups), jnp.float32),
        interpret=interpret,
    )(xr)

    # Finalize on XLA (O(B*G)): Welford merge of the per-block
    # (sum, shifted-M2) pairs — var stays stable for large-mean inputs.
    cg = c // groups
    n_rows = jnp.minimum(blk, hw - blk * jnp.arange(nblk)).astype(jnp.float32)
    n_b = n_rows[None, :, None] * cg            # [1, nblk, 1] counts
    n = float(hw * cg)
    gsum_b = sums[:, :, 0]                      # [B, nblk, G]
    m2_b = sums[:, :, 1]                        # [B, nblk, G]
    mean_g = jnp.sum(gsum_b, axis=1) / n        # [B, G]
    mean_b = gsum_b / n_b
    m2 = jnp.sum(m2_b + n_b * (mean_b - mean_g[:, None, :]) ** 2, axis=1)
    var_g = m2 / n
    rstd_g = jax.lax.rsqrt(jnp.maximum(var_g, 0.0) + eps)
    # [B, 1, C] so the per-sample block equals the array in the minor two
    # dims (Pallas TPU block-shape rule).
    mean_c = jnp.repeat(mean_g, c // groups, axis=-1)[:, None, :]
    rstd_c = jnp.repeat(rstd_g, c // groups, axis=-1)[:, None, :]

    # Pass 2: normalize + affine + SiLU per block.
    out = pl.pallas_call(
        functools.partial(_gn_norm_kernel, apply_silu=apply_silu),
        grid=(b, nblk),
        in_specs=[
            pl.BlockSpec((1, blk, c), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, 1, c), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, 1, c), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, c), lambda i, j: (0, 0)),
            pl.BlockSpec((1, c), lambda i, j: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, blk, c), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((b, hw, c), x.dtype),
        interpret=interpret,
    )(xr, mean_c, rstd_c, scale.reshape(1, c), bias.reshape(1, c))
    return out.reshape(orig_shape), mean_c, rstd_c


def _impl(x: jax.Array, scale: jax.Array, bias: jax.Array,
          groups: int, eps: float, apply_silu: bool,
          interpret: bool, force_pallas: bool) -> jax.Array:
    return _impl_stats(x, scale, bias, groups, eps, apply_silu,
                       interpret, force_pallas)[0]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _fused_gn_silu(x, scale, bias, groups, eps, apply_silu, interpret,
                   force_pallas):
    return _impl(x, scale, bias, groups, eps, apply_silu, interpret,
                 force_pallas)


def _gn_fwd(x, scale, bias, groups, eps, apply_silu, interpret, force_pallas):
    out, mean_c, rstd_c = _impl_stats(x, scale, bias, groups, eps,
                                      apply_silu, interpret, force_pallas)
    return out, (x, scale, bias, mean_c, rstd_c)


def _gn_bwd(groups, eps, apply_silu, interpret, force_pallas, res, g):
    # Pallas-path backward: dedicated tiled kernels reusing the saved
    # per-group stats (VERDICT r4 #3) — two passes over (x, g) instead
    # of XLA re-deriving the whole forward chain (which recomputes the
    # statistics reduction as well). FLAXDIFF_FUSED_NORM_BWD=xla is the
    # A/B escape hatch mirroring FLAXDIFF_FUSED_NORM. XLA-path forwards
    # (no saved stats) keep the recompute-through-autodiff backward.
    x, scale, bias, mean_c, rstd_c = res
    if (mean_c is not None
            and os.environ.get("FLAXDIFF_FUSED_NORM_BWD") != "xla"):
        # the env interpret hook must reach the backward too — a fwd
        # that ran interpreted would otherwise hand Mosaic a CPU build
        if _fused_norm_interpret():
            interpret = True
        return _pallas_gn_silu_bwd(x, scale, bias, mean_c, rstd_c, g,
                                   groups, apply_silu, interpret)
    _, vjp = jax.vjp(
        lambda x_, s_, b_: _xla_groupnorm_silu(x_, s_, b_, groups, eps,
                                               apply_silu), x, scale, bias)
    return vjp(g)


_fused_gn_silu.defvjp(_gn_fwd, _gn_bwd)


def fused_groupnorm_silu(x: jax.Array, scale: jax.Array, bias: jax.Array,
                         groups: int = 8, eps: float = 1e-6,
                         apply_silu: bool = True,
                         interpret: bool = False,
                         force_pallas: bool = False) -> jax.Array:
    """x: [B, H, W, C] (or [B, L, C]); scale/bias: [C]. Differentiable."""
    return _fused_gn_silu(x, scale, bias, groups, eps, apply_silu,
                          interpret, force_pallas)

"""Grain loader assembly + collation.

Capability parity with reference flaxdiff/data/dataloaders.py:261-640
(get_dataset_grain: IndexSampler sharded by jax process, worker processes,
shape-normalizing collate with fallback dummy batches, per-process batch
slicing). The trainer consumes host-local numpy batches and builds global
arrays itself (DiffusionTrainer.put_batch), so loaders here stop at the
host boundary — no per-step device sync.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Iterator, Optional, Tuple

import jax
import numpy as np

from .dataplane import _host_asarray
from .sources.base import MediaDataset


def collate(samples, sample_key: str = "image") -> Dict[str, Any]:
    """Stack sample dicts into a batch dict; tokenized text stacks per
    sub-key (reference dataloaders.py:85-252)."""
    if not samples:
        raise ValueError("empty batch")
    batch: Dict[str, Any] = {}
    first = samples[0]
    for key in first:
        vals = [s[key] for s in samples]
        if isinstance(first[key], dict):
            batch[key] = {k: np.stack([v[k] for v in vals])
                          for k in first[key]}
        elif isinstance(first[key], str):
            batch[key] = list(vals)
        else:
            batch[key] = np.stack(vals)
    return batch


def _destring(batch: Dict[str, Any]) -> Dict[str, Any]:
    """Convert numpy unicode arrays (grain's stacked strings) to lists."""
    def fix(v):
        if isinstance(v, dict):
            return {k: fix(x) for k, x in v.items()}
        if isinstance(v, np.ndarray) and v.dtype.kind in ("U", "S"):
            return [str(s) for s in v.tolist()]
        return v
    return {k: fix(v) for k, v in batch.items()}


def to_trainer_batch(batch: Dict[str, Any]) -> Dict[str, Any]:
    """Rename the media key to the trainer's contract: train_step reads
    batch["sample"] (train_step.py:57) and conditioning under "cond"."""
    out: Dict[str, Any] = {}
    for key, v in batch.items():
        if key in ("image", "video"):
            out["sample"] = v
        elif key == "text" and not isinstance(v, list):
            out.setdefault("cond", {})["text"] = v
        else:
            out[key] = v
    return out


def fallback_batch(reference_batch: Dict[str, Any]) -> Dict[str, Any]:
    """Zero-filled batch with the same structure — injected when a batch
    fails to decode (reference dataloaders.py:203-247)."""
    def zero(v):
        if isinstance(v, dict):
            return {k: zero(x) for k, x in v.items()}
        if isinstance(v, list):
            return [""] * len(v)
        return np.zeros_like(v)
    return {k: zero(v) for k, v in reference_batch.items()}


class GrainIterator:
    """Stateful epoch iterator over a GrainLoader — the resumable unit
    of the deterministic data plane (ISSUE 17).

    Exposes `state_dict()/load_state_dict()` (epoch, in-epoch offset,
    per-epoch production history) and `seek(cursor)` addressing the
    stream by GLOBAL batch index. A seek jumps whole epochs for free
    (each epoch's sampler is rebuilt from `seed + epoch`, so entering
    an epoch costs nothing) and replay-skips within the target epoch —
    re-decoding at most one epoch's worth of batches, and reproducing
    the exact decode/fallback sequence an uninterrupted run saw, which
    is what makes the replay bit-identical.

    Epoch production counts are recorded as epochs complete so a seek
    across epochs that produced an off-nominal batch count (a decode
    failure swallowed before any good batch existed) still lands on the
    right boundary; past recorded history, epochs are assumed nominal —
    which holds whenever record-level quarantine (placeholder records,
    geometry preserved) is on, the production configuration."""

    def __init__(self, loader: "GrainLoader", seed: int = 0):
        self.loader = loader
        self.seed = seed
        self.epoch = 0
        self.offset = 0                  # batches yielded this epoch
        self.epoch_counts: list = []     # produced per COMPLETED epoch
        self.last_good: Optional[Dict[str, Any]] = None
        self._it = None

    def _epoch_iter(self):
        return iter(self.loader.make_loader(self.seed + self.epoch))

    def __iter__(self) -> "GrainIterator":
        return self

    def __next__(self) -> Dict[str, Any]:
        while True:
            if self._it is None:
                self._it = self._epoch_iter()
            try:
                batch = to_trainer_batch(_destring(next(self._it)))
            except StopIteration:
                if self.offset == 0 and self.last_good is None:
                    # fewer records than one (drop_remainder) batch: an
                    # epoch yields nothing and the loop would spin forever
                    raise ValueError(
                        "grain epoch produced no batches — dataset "
                        "smaller than one batch (drop_remainder)? records "
                        "per process insufficient for the local batch size")
                self.epoch_counts.append(self.offset)
                self.epoch += 1
                self.offset = 0
                self._it = None
                continue
            except Exception:
                # decode/transform failure: keep the loop fed
                # (reference dataloaders.py:203-247)
                if self.last_good is None:
                    continue
                batch = fallback_batch(self.last_good)
            self.last_good = batch
            self.offset += 1
            return batch

    @property
    def cursor(self) -> int:
        """Global batch index of the NEXT batch."""
        return sum(self.epoch_counts) + self.offset

    def seek(self, cursor: int) -> None:
        """Position so the next batch is global batch index `cursor`."""
        epoch, remaining = 0, int(cursor)
        for count in self.epoch_counts:
            if remaining < count:
                break
            remaining -= count
            epoch += 1
        else:
            bpe = max(self.loader.batches_per_epoch, 1)
            epoch += remaining // bpe
            remaining %= bpe
        self.epoch = epoch
        self.epoch_counts = self.epoch_counts[:epoch]
        self.offset = 0
        self.last_good = None
        self._it = self._epoch_iter()
        for _ in range(remaining):       # replay-skip inside the epoch
            next(self)

    def state_dict(self) -> Dict[str, Any]:
        return {"seed": self.seed, "epoch": self.epoch,
                "offset": self.offset,
                "epoch_counts": list(self.epoch_counts)}

    def load_state_dict(self, sd: Dict[str, Any]) -> None:
        self.seed = sd.get("seed", self.seed)
        self.epoch_counts = list(sd.get("epoch_counts", ()))
        self.epoch = int(sd.get("epoch", 0))
        self.epoch_counts = self.epoch_counts[:self.epoch]
        self.offset = 0
        self.last_good = None
        self._it = self._epoch_iter()
        for _ in range(int(sd.get("offset", 0))):
            next(self)


@dataclasses.dataclass
class GrainLoader:
    """Restartable epoch iterator over a grain DataLoader. Batches come
    out in trainer contract form ({"sample": ..., "cond"/"text": ...}).
    Calling it returns a `GrainIterator` (a normal iterator, plus
    `seek`/`state_dict` for the deterministic data plane)."""

    make_loader: Callable[[int], Any]     # seed -> grain DataLoader
    batches_per_epoch: int

    def __call__(self, seed: int = 0) -> GrainIterator:
        return GrainIterator(self, seed=seed)

    def iter_from(self, seed: int = 0, cursor: int = 0) -> GrainIterator:
        """Iterator positioned at global batch index `cursor` — the
        restart/rollback entry point (`ResumableStream` uses the
        iterator's own `seek` when rewinding in place)."""
        it = GrainIterator(self, seed=seed)
        if cursor:
            it.seek(cursor)
        return it


def get_dataset_grain(dataset: MediaDataset,
                      batch_size: int,
                      image_size: int = 64,
                      worker_count: int = 0,
                      seed: int = 0,
                      num_epochs: Optional[int] = None,
                      drop_remainder: bool = True,
                      augment_kwargs: Optional[dict] = None,
                      worker_buffer_size: int = 1,
                      read_threads: Optional[int] = None,
                      read_buffer_size: Optional[int] = None) -> Dict[str, Any]:
    """Assemble the sharded grain pipeline for one MediaDataset.

    Returns {"train": callable -> iterator, "train_len": n_records,
    "local_batch_size": per-process batch} (reference
    dataloaders.py:261-349). worker_buffer_size / read_threads /
    read_buffer_size are the grain throughput knobs the reference tunes
    from its CLI (reference training.py:84-99: 32 workers / 140 read
    threads / read buffer 96 / worker buffer 100 at corpus scale).
    """
    import grain.python as pygrain

    source = dataset.get_source()
    transform = dataset.get_augmenter(
        image_size=image_size, **(augment_kwargs or {}))
    filt = dataset.augmenter.create_filter()

    if batch_size % jax.process_count():
        raise ValueError(
            f"batch {batch_size} not divisible by {jax.process_count()} "
            "processes")
    local_bs = batch_size // jax.process_count()

    class _Map(pygrain.RandomMapTransform):
        def random_map(self, record, rng: np.random.Generator):
            return transform(record, rng=rng)

    ops = []
    if filt is not None:
        class _Filter(pygrain.FilterTransform):
            def filter(self, record) -> bool:
                return filt(record)
        ops.append(_Filter())
    ops.append(_Map())
    # grain's Batch stacks every leaf (strings become <U numpy arrays);
    # GrainLoader converts string arrays back to lists downstream.
    ops.append(pygrain.Batch(batch_size=local_bs,
                             drop_remainder=drop_remainder))

    def make_loader(epoch_seed: int,
                    shard: Optional[Tuple[int, int]] = None):
        # default: launch-time jax process world; an explicit
        # (rank, size) shard override re-shards the index sampler for
        # a post-shrink elastic world (the `reshard` factory below)
        shard_options = (
            pygrain.ShardOptions(shard_index=shard[0],
                                 shard_count=shard[1],
                                 drop_remainder=True)
            if shard is not None
            else pygrain.ShardByJaxProcess(drop_remainder=True))
        sampler = pygrain.IndexSampler(
            num_records=len(source),
            shuffle=True,
            seed=epoch_seed,
            num_epochs=1,
            shard_options=shard_options,
        )
        read_options = None
        if read_threads is not None or read_buffer_size is not None:
            read_options = pygrain.ReadOptions(
                **({"num_threads": read_threads}
                   if read_threads is not None else {}),
                **({"prefetch_buffer_size": read_buffer_size}
                   if read_buffer_size is not None else {}))
        return pygrain.DataLoader(
            data_source=source,
            sampler=sampler,
            operations=ops,
            worker_count=worker_count,
            worker_buffer_size=worker_buffer_size,
            read_options=read_options,
        )

    n = len(source) // jax.process_count()

    def reshard(rank: int, size: int) -> GrainLoader:
        """Rebuild the grain pipeline for a changed world: the index
        sampler re-shards over the surviving (rank, size) instead of
        the launch-time jax process world, so an elastic shrink
        re-partitions the dataset across survivors with no records
        orphaned on dead hosts. Batch geometry is unchanged — each
        survivor still emits `local_batch_size` batches."""
        per = len(source) // max(size, 1)
        return GrainLoader(
            lambda es: make_loader(es, shard=(rank, size)),
            max(per // local_bs, 1))

    return {
        "train": GrainLoader(make_loader, max(n // local_bs, 1)),
        "train_len": len(source),
        "local_batch_size": local_bs,
        "global_batch_size": batch_size,
        "reshard": reshard,
    }


def make_batch_iterator(images: np.ndarray,
                        batch_size: int,
                        labels=None,
                        seed: int = 0) -> Iterator[Dict[str, Any]]:
    """Minimal in-memory infinite batch iterator (no grain) for quick runs
    and benchmarks."""
    rng = np.random.default_rng(seed)
    n = len(images)
    while True:
        idx = rng.integers(0, n, size=batch_size)
        batch = {"sample": _host_asarray(images[idx])}
        if labels is not None:
            batch["text"] = [labels[i] for i in idx]
        yield batch

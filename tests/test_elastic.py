"""Elastic world membership (resilience/elastic.py) on CPU: membership
rounds over in-memory/file transports, the member-scoped coordinator
transport, quorum decision rules, the goodput reclaimed account, ledger
round-trips through the verify CLI, and the fit-loop seam contract
(elastic enabled adds ZERO host syncs on healthy steps).

The real 2-process kill/join/evict scenarios live in
tests/test_multiprocess_elastic.py (chaos marker); everything here is
single-process so the protocol runs in tier-1.
"""
import json
import threading

import numpy as np
import pytest

from flaxdiff_tpu import resilience as R
from flaxdiff_tpu.trainer.checkpoints import Checkpointer


def _all(*fns):
    """Run each fn on its own thread (one per simulated host); re-raise
    the first failure; return results in fn order."""
    out = [None] * len(fns)
    errs = []

    def run(i, fn):
        try:
            out[i] = fn()
        except Exception as e:  # noqa: BLE001 — surfaced below
            errs.append(e)

    ts = [threading.Thread(target=run, args=(i, f))
          for i, f in enumerate(fns[1:], 1)]
    for t in ts:
        t.start()
    run(0, fns[0])
    for t in ts:
        t.join()
    if errs:
        raise errs[0]
    return out


def _managers(n, ledger=None, cfg=None, transports=None):
    tps = transports or R.InMemoryTransport.make_world(n)
    cfg = cfg or R.ElasticConfig(shrink_window=0.4, vote_timeout=5.0)
    return [R.ElasticWorldManager(t, ledger=ledger, config=cfg)
            for t in tps], tps


# -- FileTransport ------------------------------------------------------------

def test_file_transport_collectives_and_kv(tmp_path):
    tps = [R.FileTransport(str(tmp_path), rank=i, world=2,
                           poll_interval=0.01) for i in range(2)]
    assert _all(lambda: tps[0].barrier("b", 5.0),
                lambda: tps[1].barrier("b", 5.0)) == [None, None]
    got = _all(lambda: tps[0].allgather_json("g", {"r": 0}, 5.0),
               lambda: tps[1].allgather_json("g", {"r": 1}, 5.0))
    assert got[0] == got[1] == [{"r": 0}, {"r": 1}]
    bc = _all(lambda: tps[0].broadcast_json("bc", [1, 2], 5.0),
              lambda: tps[1].broadcast_json("bc", None, 5.0))
    assert bc == [[1, 2], [1, 2]]
    # point primitives: a dead member is a bounded None, not a hang
    tps[0].put_json("k", {"x": 1})
    assert tps[1].get_json("k", timeout=2.0) == {"x": 1}
    assert tps[1].get_json("missing", timeout=0.05) is None
    tps[0].offer_json("o", 7)
    assert tps[1].poll_json("o", 0, timeout=2.0) == 7
    assert tps[1].poll_json("o", 1, timeout=0.05) is None


def test_file_transport_barrier_times_out_on_dead_member(tmp_path):
    t0 = R.FileTransport(str(tmp_path), rank=0, world=2,
                         poll_interval=0.01)
    with pytest.raises(R.BarrierTimeout):
        t0.barrier("alone", 0.3)


# -- membership rounds --------------------------------------------------------

def test_shrink_commits_world_changed_and_picks_consensus_step(tmp_path):
    led = R.StepLedger(str(tmp_path))
    led.record_commit(2, world_size=3)
    led.record_commit(4, world_size=3)
    mgrs, _ = _managers(3, ledger=led)
    # rank 2 is dead: never enters the round
    c0, c1 = _all(lambda: mgrs[0].shrink("barrier timeout"),
                  lambda: mgrs[1].shrink("barrier timeout"))
    for c in (c0, c1):
        assert c.kind == "shrink"
        assert c.members == [0, 1] and c.removed == [2]
        assert c.step == 4 and c.epoch == 1
    assert mgrs[0].members == mgrs[1].members == [0, 1]
    assert mgrs[0].world_epoch == 1
    wc = led.world_changes()
    assert len(wc) == 1
    assert wc[0]["change"] == "shrink" and wc[0]["world"] == 2
    assert wc[0]["members"] == [0, 1] and wc[0]["step"] == 4


def test_shrink_with_everyone_present_is_abandoned():
    mgrs, _ = _managers(2)
    c0, c1 = _all(lambda: mgrs[0].shrink("spurious"),
                  lambda: mgrs[1].shrink("spurious"))
    assert c0 is None and c1 is None
    assert mgrs[0].members == [0, 1] and mgrs[0].world_epoch == 0


def test_shrink_respects_min_world():
    cfg = R.ElasticConfig(shrink_window=0.3, vote_timeout=3.0,
                          min_world=2)
    mgrs, _ = _managers(2, cfg=cfg)
    # rank 1 dead: only 1 survivor < min_world -> no transition
    assert mgrs[0].shrink("peer lost") is None
    assert mgrs[0].members == [0, 1]


def test_request_join_and_maybe_admit_grow_the_world(tmp_path):
    led = R.StepLedger(str(tmp_path))
    tps = R.InMemoryTransport.make_world(2)
    cfg = R.ElasticConfig(shrink_window=0.3, vote_timeout=5.0,
                          admit_timeout=10.0)
    incumbent = R.ElasticWorldManager(tps[0], ledger=led, config=cfg,
                                      members=[0])
    joiner = R.ElasticWorldManager(tps[1], ledger=led, config=cfg,
                                   members=[0])
    jr, admitted = _all(lambda: joiner.request_join(),
                        lambda: incumbent.maybe_admit(current_step=6))
    assert jr.kind == "grow" and jr.members == [0, 1] and jr.step == 6
    assert admitted is not None and admitted.added == [1]
    assert incumbent.members == joiner.members == [0, 1]
    assert incumbent.world_epoch == joiner.world_epoch == 1
    grow = led.world_changes()[-1]
    assert grow["change"] == "grow" and grow["world"] == 2
    # a boundary with no parked joiner is a cheap no-op on both members
    none0, none1 = _all(lambda: incumbent.maybe_admit(current_step=8),
                        lambda: joiner.maybe_admit(current_step=8))
    assert none0 is None and none1 is None


def test_quorum_minority_evicted_majority_rolls_back(tmp_path):
    led = R.StepLedger(str(tmp_path))
    led.record_commit(4, world_size=3)
    mgrs, _ = _managers(3, ledger=led)
    # 1/3 anomalous: the outlier is evicted, survivors untouched
    q = _all(lambda: mgrs[0].quorum_round(False, step=6),
             lambda: mgrs[1].quorum_round(True, step=6),
             lambda: mgrs[2].quorum_round(False, step=6))
    assert [d.kind for d in q] == ["evict", "evicted", "evict"]
    assert q[0].change is not None and q[0].change.members == [0, 2]
    assert mgrs[0].members == [0, 2] and mgrs[0].world_epoch == 1
    assert led.quorum_decisions()[-1]["decision"] == "evict"
    assert led.world_changes()[-1]["change"] == "evict"
    # 2/2 anomalous: pod-sick majority -> rollback-all to consensus
    q2 = _all(lambda: mgrs[0].quorum_round(True, step=8),
              lambda: mgrs[2].quorum_round(True, step=8))
    assert all(d.kind == "rollback_all" for d in q2)
    assert q2[0].step == 4
    assert led.quorum_decisions()[-1]["decision"] == "rollback_all"
    # healthy round: nothing happens, no ledger traffic
    n_entries = len(led.entries())
    q3 = _all(lambda: mgrs[0].quorum_round(False),
              lambda: mgrs[2].quorum_round(False))
    assert all(d.kind == "none" for d in q3)
    assert len(led.entries()) == n_entries


def test_quorum_solo_world_is_its_own_quorum(tmp_path):
    led = R.StepLedger(str(tmp_path))
    led.record_commit(2, world_size=1)
    mgr = R.ElasticWorldManager(R.InMemoryTransport.make_world(1)[0],
                                ledger=led)
    assert mgr.quorum_round(False).kind == "none"
    d = mgr.quorum_round(True, step=3)
    assert d.kind == "rollback_all" and d.step == 2


# -- member-scoped coordinator transport --------------------------------------

def test_member_transport_commit_round_survives_a_shrink(tmp_path):
    """The two-phase commit keeps working across an elastic transition:
    before the shrink, a world-of-3 commit needs all three votes; after
    rank 2 dies and the survivors shrink, the SAME coordinators (reborn
    into the new epoch namespace) commit as a world of 2 — and the
    commit entry records the shrunken world size."""
    led = R.StepLedger(str(tmp_path))
    mgrs, _ = _managers(3, ledger=led)
    coords = [R.RestartCoordinator(R.MemberTransport(m),
                                   barrier_timeout=5.0) for m in mgrs]
    got = _all(lambda: coords[0].commit(2, led),
               lambda: coords[1].commit(2, led),
               lambda: coords[2].commit(2, led))
    assert got == [2, 2, 2]
    assert [e["world"] for e in led.entries()
            if e.get("kind") == "commit"] == [3]

    # rank 2 dies; 0 and 1 shrink, then their coordinators are reborn
    _all(lambda: mgrs[0].shrink("rank 2 lost"),
         lambda: mgrs[1].shrink("rank 2 lost"))
    for c in coords[:2]:
        c.lost = True       # what a real barrier timeout would have set
        c.rebirth()
        assert not c.lost
    got = _all(lambda: coords[0].commit(4, led),
               lambda: coords[1].commit(4, led))
    assert got == [4, 4]
    worlds = [e["world"] for e in led.entries()
              if e.get("kind") == "commit"]
    assert worlds == [3, 2]
    assert led.committed_steps() == [2, 4]


def test_member_transport_rejects_non_member():
    mgrs, _ = _managers(2)
    _all(lambda: mgrs[0].quorum_round(False, step=1),
         lambda: mgrs[1].quorum_round(True, step=1))   # 1/2 -> evict 1
    evicted = R.MemberTransport(mgrs[1])
    with pytest.raises(R.CoordinationError):
        evicted.barrier("nope", 0.1)


# -- goodput reclaimed account ------------------------------------------------

def test_goodput_reclaimed_is_outside_the_closure_and_persists(tmp_path):
    from flaxdiff_tpu.telemetry.goodput import GoodputLedger
    path = str(tmp_path / "goodput.json")
    g = GoodputLedger(path)
    g.record_productive(10.0)
    g.record_badput("elastic_shrink", 2.0)
    g.record_reclaimed("elastic_shrink", 30.0)
    t = g.totals()
    # reclaimed seconds never happened: they must NOT enter the
    # productive+badput=total closure
    assert t["total_s"] == pytest.approx(12.0)
    assert t["reclaimed_s"] == {"elastic_shrink": 30.0}
    assert t["reclaimed_total_s"] == pytest.approx(30.0)
    snap = g.snapshot()
    assert snap["goodput/reclaimed_s"] == pytest.approx(30.0)
    assert snap["goodput/reclaimed/elastic_shrink_s"] == pytest.approx(30.0)
    g.persist()
    # next incarnation resumes the reclaimed account too
    g2 = GoodputLedger(path)
    g2.record_reclaimed("quorum_rollback", 5.0)
    t2 = g2.totals()
    assert t2["reclaimed_s"]["elastic_shrink"] == pytest.approx(30.0)
    assert t2["reclaimed_s"]["quorum_rollback"] == pytest.approx(5.0)
    assert t2["incarnations"] == 2


def test_reclaimed_estimate_uses_ledger_and_startup_badput(tmp_path):
    from flaxdiff_tpu.telemetry.goodput import GoodputLedger
    led = R.StepLedger(str(tmp_path))
    led.record_commit(2, world_size=2)
    mgr = R.ElasticWorldManager(
        R.InMemoryTransport.make_world(1)[0], ledger=led,
        config=R.ElasticConfig(restart_cost_estimate=7.0))
    g = GoodputLedger()
    g.record_badput("compile", 3.0)
    g.record_badput("restart", 1.0)
    est = mgr.reclaimed_estimate(2, transition_s=0.5, goodput=g)
    # >= startup badput + configured relaunch cost - transition cost;
    # the work-since-commit term only adds to it
    assert est >= 3.0 + 1.0 + 7.0 - 0.5
    # with no committed step the work-lost term drops out but the
    # startup counterfactual stands
    est2 = mgr.reclaimed_estimate(None, transition_s=0.5, goodput=g)
    assert est2 == pytest.approx(3.0 + 1.0 + 7.0 - 0.5)


# -- ledger round-trip through the verify CLI (satellite) ---------------------

def test_world_changed_round_trips_through_verify_cli(tmp_path, capsys):
    led = R.StepLedger(str(tmp_path))
    led.record_commit(2, world_size=2)
    led.record_world_changed("shrink", 1, [0], 2, reason="host 1 lost",
                             extra={"removed": [1]})
    led.record_quorum({"0": False, "1": True}, "evict", step=4)
    (tmp_path / "2").mkdir()    # a (bogus) step dir so the CLI scans
    from scripts.verify_checkpoint import main as verify_main
    rc = verify_main([str(tmp_path), "--all-steps", "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1      # the bogus step dir is (correctly) not intact
    wc = out["ledger"]["world_changes"]
    assert len(wc) == 1 and wc[0]["change"] == "shrink"
    assert wc[0]["members"] == [0] and wc[0]["step"] == 2
    qd = out["ledger"]["quorum_decisions"]
    assert len(qd) == 1 and qd[0]["decision"] == "evict"
    assert qd[0]["votes"] == {"0": False, "1": True}


def test_diagnose_run_renders_elasticity_section(tmp_path, capsys):
    """ISSUE 12 satellite: diagnose_run gains an Elasticity section —
    world-size timeline, per-transition cost + reclaimed estimate, and
    quorum decisions — in text and --json."""
    tel = tmp_path / "tel"
    tel.mkdir()
    rows = [
        {"type": "step_phases", "step": 1, "host": 0.1, "wall": 0.2},
        {"type": "elastic_transition", "kind": "shrink", "epoch": 1,
         "world": 1, "members": [0], "removed": [1], "added": [],
         "step": 2, "duration_s": 3.5, "reclaimed_s": 41.0,
         "reason": "commit barrier timeout"},
        {"type": "quorum_decision", "kind": "evict", "step": 6,
         "votes": {"0": False, "1": True}},
    ]
    with open(tel / "telemetry.jsonl", "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")
    with open(tel / "goodput.json", "w") as f:
        json.dump({"incarnations": 1, "productive_s": 100.0,
                   "badput_s": {"elastic_shrink": 3.5},
                   "reclaimed_s": {"elastic_shrink": 41.0}}, f)
    from scripts.diagnose_run import main as diagnose_main
    assert diagnose_main([str(tel)]) == 0
    out = capsys.readouterr().out
    assert "== Elasticity ==" in out
    assert "shrink" in out and "world-size timeline: 1" in out
    assert "elastic_shrink" in out and "41.00" in out
    assert "quorum @ step 6: evict" in out
    # --json carries the structured report
    assert diagnose_main([str(tel), "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["elasticity"]["world_timeline"] == [1]
    assert doc["elasticity"]["transitions"][0]["reclaimed_s"] == 41.0
    assert doc["elasticity"]["quorum_decisions"][0]["kind"] == "evict"
    assert doc["elasticity"]["reclaimed_s"] == {"elastic_shrink": 41.0}


# -- fit-loop integration -----------------------------------------------------

def _tiny_trainer(mesh, ckpt=None, elastic=None, **cfg_kw):
    import flax.linen as nn
    import jax.numpy as jnp
    import optax

    from flaxdiff_tpu.predictors import EpsilonPredictionTransform
    from flaxdiff_tpu.schedulers import CosineNoiseSchedule
    from flaxdiff_tpu.trainer import DiffusionTrainer, TrainerConfig

    class Tiny(nn.Module):
        @nn.compact
        def __call__(self, x, t, cond=None):
            h = nn.Conv(8, (3, 3))(x)
            return nn.Conv(x.shape[-1], (3, 3))(jnp.tanh(h))

    model = Tiny()
    return DiffusionTrainer(
        apply_fn=lambda p, x, t, c: model.apply({"params": p}, x, t, None),
        init_fn=lambda key: model.init(
            key, jnp.zeros((1, 8, 8, 1)), jnp.zeros((1,)))["params"],
        tx=optax.adam(1e-3),
        schedule=CosineNoiseSchedule(timesteps=100),
        transform=EpsilonPredictionTransform(), mesh=mesh,
        config=TrainerConfig(normalize=False, **cfg_kw),
        checkpointer=ckpt, elastic=elastic)


def _data(rng, batch=8):
    while True:
        yield {"sample": rng.normal(size=(batch, 8, 8, 1))
               .astype(np.float32)}


def _solo_elastic_world(tmp_path):
    transport = R.InMemoryTransport.make_world(1)[0]
    mgr = R.ElasticWorldManager(
        transport, config=R.ElasticConfig(shrink_window=0.2,
                                          vote_timeout=2.0))
    coord = R.RestartCoordinator(R.MemberTransport(mgr),
                                 barrier_timeout=2.0)
    ck = Checkpointer(str(tmp_path), coordinator=coord)
    mgr.ledger = ck.ledger
    mgr.valid_steps = ck.locally_valid_steps
    return mgr, ck


def test_elastic_healthy_fit_adds_zero_host_syncs(mesh, tmp_path,
                                                  monkeypatch, rng):
    """ISSUE 12 satellite: the shrink/re-admit machinery is KV-side
    only — a healthy elastic fit performs EXACTLY the same seam-counted
    host syncs as the identical non-elastic fit, and commits into the
    ledger the same way."""
    from flaxdiff_tpu.trainer import trainer as trainer_mod

    class Counting:
        def __init__(self, real):
            self.real, self.calls = real, 0

        def __call__(self, *a, **k):
            self.calls += 1
            return self.real(*a, **k)

    counts = {}
    for run in ("plain", "elastic"):
        block = Counting(trainer_mod._block_until_ready)
        fetch = Counting(trainer_mod._fetch_losses)
        monkeypatch.setattr(trainer_mod, "_block_until_ready", block)
        monkeypatch.setattr(trainer_mod, "_fetch_losses", fetch)
        # depth > total_steps: the bounded-dispatch pop never triggers,
        # so the block count cannot drift with scheduler noise between
        # the two runs (the test_pipeline_loop isolation trick)
        if run == "elastic":
            mgr, ck = _solo_elastic_world(tmp_path / run)
            tr = _tiny_trainer(mesh, ckpt=ck, elastic=mgr, log_every=2,
                               keep_best_state=False, pipeline_depth=16)
        else:
            ck = Checkpointer(str(tmp_path / run), use_ledger=True)
            tr = _tiny_trainer(mesh, ckpt=ck, log_every=2,
                               keep_best_state=False, pipeline_depth=16)
        hist = tr.fit(_data(rng), total_steps=6, save_every=2)
        ck.wait_until_finished()
        counts[run] = (block.calls, fetch.calls)
        assert np.isfinite(hist["final_loss"])
        assert hist["coordination_lost"] is False
        assert hist["elastic"] == []
        assert ck.ledger.committed_steps() == [2, 4, 6]
        ck.close()
    assert counts["elastic"] == counts["plain"]


def test_forced_mesh_rebuild_reshards_and_keeps_training(mesh, rng):
    """The elastic mesh-rebuild path: a trainer on the 8-device
    ("data", "fsdp") mesh re-forms onto the 1-D local 'data' mesh,
    re-jits, and keeps training with the SAME state values."""
    import jax
    tr = _tiny_trainer(mesh, log_every=4, keep_best_state=False)
    l0 = float(jax.device_get(tr.train_step(next(_data(rng)))))
    assert np.isfinite(l0)
    assert tr._rebuild_world_mesh(force=True) is True
    assert tr.mesh.axis_names == ("data",)
    assert tr.mesh.devices.size == len(jax.local_devices())
    # the live state survived the re-shard and the new program runs
    assert int(jax.device_get(tr.state.step)) == 1
    l1 = float(jax.device_get(tr.train_step(next(_data(rng)))))
    assert np.isfinite(l1)
    # an already-local 1-D mesh is a no-op without force
    assert tr._rebuild_world_mesh() is False


def test_elastic_quorum_rollback_all_in_fit(tmp_path, rng):
    """Solo-world pod quorum inside fit: a hard numerics anomaly under
    anomaly_action='rollback' takes the QUORUM path (world of one = its
    own quorum), restores the consensus committed step, and accounts
    the transition in the quorum_rollback badput bucket."""
    from flaxdiff_tpu.parallel import create_mesh
    mgr, ck = _solo_elastic_world(tmp_path / "q")
    plan = R.FaultPlan([R.FaultSpec("numerics.nan", at=(3,),
                                    error="flag", times=1)])
    ev = R.EventLog("elastic-test")
    with R.use_event_log(ev), plan.installed():
        tr = _tiny_trainer(create_mesh(axes={"data": -1}), ckpt=ck,
                           elastic=mgr, log_every=4, keep_best_state=False,
                           numerics_cadence=2, anomaly_action="rollback")
        hist = tr.fit(_data(rng), total_steps=8, save_every=2)
    ck.wait_until_finished()
    assert hist.get("quorum") == ["rollback_all"]
    assert ev.count("quorum_rollback", "elastic.quorum") == 1
    assert hist["goodput"]["badput_s"].get("quorum_rollback", 0.0) > 0.0
    # the ledger recorded the pod (of one)'s decision
    assert ck.ledger.quorum_decisions()[-1]["decision"] == "rollback_all"
    assert np.isfinite(hist["final_loss"])
    ck.close()


def test_elastic_quorum_rides_log_step_with_cadence_zero(tmp_path, rng):
    """ISSUE 16 satellite — the numerics_cadence=0 quorum hole: with no
    cadence step, a hard non-finite anomaly surfaces only at the
    log-step loss-window fetch. That anomaly must enter the pod quorum
    (collective vote at every log step) instead of falling back to a
    unilateral local rollback that would fork the pod."""
    from flaxdiff_tpu.parallel import create_mesh
    mgr, ck = _solo_elastic_world(tmp_path / "q0")
    # step.nan poisons the loss the NEXT readback sees — with
    # cadence 0 that readback IS the log-step window fetch
    plan = R.FaultPlan([R.FaultSpec("step.nan", at=(3,),
                                    error="flag", times=1)])
    ev = R.EventLog("elastic-test")
    with R.use_event_log(ev), plan.installed():
        tr = _tiny_trainer(create_mesh(axes={"data": -1}), ckpt=ck,
                           elastic=mgr, log_every=2, keep_best_state=False,
                           numerics_cadence=0, anomaly_action="rollback")
        hist = tr.fit(_data(rng), total_steps=8, save_every=2)
    ck.wait_until_finished()
    # the anomaly was handled COLLECTIVELY: quorum decision recorded,
    # no unilateral best-state/checkpoint rollback event
    assert hist.get("quorum") == ["rollback_all"]
    assert ev.count("quorum_rollback", "elastic.quorum") == 1
    assert ev.count("rollback", "train.step") == 0
    assert hist["goodput"]["badput_s"].get("quorum_rollback", 0.0) > 0.0
    assert ck.ledger.quorum_decisions()[-1]["decision"] == "rollback_all"
    assert np.isfinite(hist["final_loss"])
    ck.close()

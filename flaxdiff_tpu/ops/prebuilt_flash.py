"""Wrapper over JAX's prebuilt TPU flash-attention kernel.

The reference calls this exact kernel
(reference flaxdiff/models/attention.py:14-17,100-102); our first-party
kernel (ops/flash_attention.py) replaces it. VERDICT r4 #2 requires the
head-to-head comparison on record — this wrapper makes the prebuilt
kernel a dispatchable backend ("prebuilt") so the flashtune harness can
time both through an identical code path, and so dispatch can route to
whichever kernel measures faster (FLAXDIFF_FLASH_IMPL=prebuilt).

Layout: the prebuilt kernel grids over [batch, heads, seq, head_dim]
(BHLD). Sequence lengths must divide the block sizes, so both are padded
to block multiples here; padded KV positions are masked via SegmentIds
(real tokens id 0, padding id 1). Padded *q* rows are left unmasked on
purpose: they attend to real keys and produce finite garbage that the
caller slices off, and their cotangents are zero (the slice's VJP
zero-pads), so ds = p*(dp-delta) = 0 — they contribute nothing to
dk/dv. Fully-masked q rows, by contrast, would hit the kernel's
mask-value path and are not worth the risk.
"""
from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp


@functools.cache
def _mod():
    from jax.experimental.pallas.ops.tpu import flash_attention as fa
    return fa


def _pad_len(n: int, block: int) -> int:
    return -(-n // block) * block


def _choose_blocks(lq: int, lk: int):
    """(block_q, block_k) for the prebuilt kernel: large sequence-capped
    blocks (the same policy our first-party kernel settled on after the
    r4 on-chip tune — 512x1024 beat 128x128 by 5.5x), env-overridable
    for on-chip A/B without a rebuild."""
    bq = int(os.environ.get("FLAXDIFF_PREBUILT_BLOCK_Q", "512"))
    bk = int(os.environ.get("FLAXDIFF_PREBUILT_BLOCK_K", "1024"))
    bq = min(bq, _pad_len(lq, 128))
    bk = min(bk, _pad_len(lk, 128))
    return bq, bk


def prebuilt_flash_attention_bhld(q: jax.Array, k: jax.Array, v: jax.Array,
                                  scale: Optional[float] = None) -> jax.Array:
    """Prebuilt TPU flash attention over [B, H, L, D] operands, fwd+bwd.

    Handles arbitrary sequence lengths by padding to block multiples
    (segment-id masking for padded KV — exact, not approximate). The
    caller handles head_dim padding policy (ops/attention.py
    _maybe_pad_head_dim) so the two flash implementations share it.
    """
    fa = _mod()
    b, h, lq, d = q.shape
    lk = k.shape[2]
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    bq, bk = _choose_blocks(lq, lk)
    lq_p, lk_p = _pad_len(lq, bq), _pad_len(lk, bk)

    def pad_seq(x, n):
        if x.shape[2] == n:
            return x
        return jnp.pad(x, ((0, 0), (0, 0), (0, n - x.shape[2]), (0, 0)))

    qp, kp, vp = pad_seq(q, lq_p), pad_seq(k, lk_p), pad_seq(v, lk_p)

    seg = None
    if lk_p != lk:
        # mask padded keys only; padded q rows stay live (see module doc)
        q_ids = jnp.zeros((b, lq_p), jnp.int32)
        kv_ids = (jnp.arange(lk_p, dtype=jnp.int32) >= lk).astype(jnp.int32)
        seg = fa.SegmentIds(q=q_ids, kv=jnp.broadcast_to(kv_ids, (b, lk_p)))

    bs = fa.BlockSizes(
        block_q=bq, block_k_major=bk, block_k=bk, block_b=1,
        block_q_major_dkv=bq, block_k_major_dkv=bk,
        block_k_dkv=bk, block_q_dkv=bq,
        block_k_major_dq=bk, block_k_dq=bk, block_q_dq=bq,
    )
    out = fa.flash_attention(qp, kp, vp, segment_ids=seg,
                             sm_scale=float(scale), block_sizes=bs)
    return out[:, :, :lq, :]


def prebuilt_available() -> bool:
    try:
        _mod()
    except Exception:
        return False
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False

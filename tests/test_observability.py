"""Incident-grade observability suite (ISSUE 18,
docs/OBSERVABILITY.md "SLO engine" / "Flight recorder" /
"Trace propagation").

Acceptance bars enforced here:
- the online SLO engine computes sliding-window attainment and
  two-window error-budget burn rates incrementally, and its
  `tier_hint` only degrades a tenant when BOTH windows burn;
- `BrownoutPolicy.tier_for` escalates an over-budget tenant and
  SHIELDS healthy tenants from a noisy neighbor's pressure — but
  never shields away the device-fault floor;
- at the front door, an over-budget tenant's requests degrade
  (burn-rate brownout) while a healthy tenant's pass untouched;
- the flight recorder dumps one cross-referenced incident bundle per
  declared incident, with per-kind cooldown + run cap suppression
  counted, and the Telemetry hub wires it end to end;
- door phase spans tile [submit, delivery] exactly: their sum
  reconciles with the `frontdoor/latency_ms` histogram total;
- loadgen's per-tenant SLO artifact is byte-stable with a pinned key
  set; `scripts/compare_runs.py` flags attainment drops (down =
  worse) and new incident bundles (any increase = worse);
- `scripts/diagnose_run.py --json` carries `schema_version` with a
  pinned top-level key set and renders SLO + Incidents sections.
"""
import json

import pytest

from flaxdiff_tpu.resilience.events import (EventLog, record_event,
                                            use_event_log)
from flaxdiff_tpu.serving import (FrontDoor, FrontDoorConfig, Replica,
                                  ReplicaPool, SampleRequest,
                                  SchedulerConfig, ServingScheduler)
from flaxdiff_tpu.serving.supervision import (BrownoutConfig,
                                              BrownoutPolicy)
from flaxdiff_tpu.telemetry import Telemetry
from flaxdiff_tpu.telemetry.flightrec import (BUNDLE_SCHEMA_VERSION,
                                              FlightRecorder,
                                              list_incidents)
from flaxdiff_tpu.telemetry.slo import SloConfig, SloEngine
from tests.test_serving import FakeEngine


def _replica(name, tel, delay=0.0, **cfg_kwargs):
    eng = FakeEngine(step_delay_s=delay)
    cfg_kwargs = {"round_steps": 4, "batch_buckets": (2,), **cfg_kwargs}
    sched = ServingScheduler(engine=eng, config=SchedulerConfig(
        **cfg_kwargs), telemetry=tel, autostart=True)
    return Replica(name, sched), eng


def _door(replicas, tel, **door_kwargs):
    return FrontDoor(ReplicaPool(replicas), telemetry=tel,
                     config=FrontDoorConfig(**door_kwargs))


# ---------------------------------------------------------------------------
# SLO engine (telemetry/slo.py)
# ---------------------------------------------------------------------------

def test_slo_sliding_windows_attainment_and_burn():
    """Attainment and burn rates are computed over a fast and a slow
    sliding window from caller-supplied timestamps; misses age out of
    the fast window first, then out of the slow one."""
    tel = Telemetry(enabled=False)
    eng = SloEngine(SloConfig(target_ms=100.0, objective=0.9,
                              fast_window_s=10.0, slow_window_s=100.0),
                    tel)
    t0 = 1000.0
    for i in range(8):
        assert eng.observe("a", 50.0, ok=True, at_s=t0 + i) is True
    assert eng.attainment("a", now=t0 + 8) == 1.0
    assert eng.burn_rates("a", now=t0 + 8) == (0.0, 0.0)
    # two misses: one over-latency success, one outright failure
    assert eng.observe("a", 500.0, ok=True, at_s=t0 + 8) is False
    assert eng.observe("a", 50.0, ok=False, at_s=t0 + 9) is False
    assert eng.attainment("a", now=t0 + 9) == pytest.approx(0.8)
    fast, slow = eng.burn_rates("a", now=t0 + 9)
    assert fast == pytest.approx(2.0)     # (1 - 0.8) / (1 - 0.9)
    assert slow == pytest.approx(2.0)
    # the misses age OUT of the fast window but stay in the slow one
    fast, slow = eng.burn_rates("a", now=t0 + 25)
    assert fast == 0.0 and slow == pytest.approx(2.0)
    # ... and eventually out of the slow window too
    assert eng.burn_rates("a", now=t0 + 200) == (0.0, 0.0)
    # per-request objective beats the engine default
    assert eng.observe("b", 150.0, at_s=t0, target_ms=200.0) is True
    assert eng.observe("b", 150.0, at_s=t0) is False
    # exported series (None tenant buckets under "default")
    eng.observe(None, 1.0, at_s=t0)
    snap = tel.registry.snapshot()
    assert snap["slo/attainment/default"] == 1.0
    assert snap["slo/observed"] == 13.0
    assert snap["slo/violations"] == 3.0
    assert "slo/burn_fast/a" in snap and "slo/burn_slow/a" in snap


def test_slo_tier_hint_needs_both_windows_and_exhaust_escalates():
    """A fast-window spike alone never degrades anyone (tier 0); both
    windows over budget is tier 1; a fast burn at `exhaust_factor`x
    budget rate is tier 2. `any_burning` goes True with the first
    over-budget tenant."""
    tel = Telemetry(enabled=False)
    cfg = SloConfig(objective=0.9, fast_window_s=10.0,
                    slow_window_s=100.0, exhaust_factor=4.0)
    eng = SloEngine(cfg, tel)
    t0 = 500.0
    assert eng.tier_hint("ghost", now=t0) == 0      # unobserved
    assert eng.tier_hint(None, now=t0) == 0
    assert eng.any_burning(now=t0) is False
    # slow window burning, fast window clean -> NOT degraded: the
    # two-window AND means a past outage alone never keeps degrading
    for i in range(4):
        eng.observe("past", 1e9, ok=False, at_s=t0 + i)
    for i in range(16):
        eng.observe("past", 1.0, ok=True, at_s=t0 + 40 + i * 0.5)
    now = t0 + 48
    fast, slow = eng.burn_rates("past", now=now)
    assert fast == 0.0 and slow >= 1.0
    assert eng.tier_hint("past", now=now) == 0
    # both windows moderately over budget -> tier 1
    for i in range(8):
        eng.observe("warm", 1.0, ok=True, at_s=t0 + i)
    for i in range(2):
        eng.observe("warm", 1e9, ok=False, at_s=t0 + 8 + i)
    fast, slow = eng.burn_rates("warm", now=t0 + 9)
    assert 1.0 <= fast < 4.0 and slow >= 1.0
    assert eng.tier_hint("warm", now=t0 + 9) == 1
    # total failure -> fast burn 10x budget rate -> tier 2 (exhausted)
    for i in range(6):
        eng.observe("dead", 1e9, ok=False, at_s=t0 + i)
    assert eng.tier_hint("dead", now=t0 + 6) == 2
    assert eng.any_burning(now=t0 + 9) is True
    snap = eng.snapshot(now=t0 + 9)
    assert set(snap) == {"past", "warm", "dead"}
    assert set(snap["warm"]) == {"attainment", "burn_fast", "burn_slow",
                                 "samples"}


def test_slo_ring_bound_keeps_counts_consistent():
    """The per-tenant sample ring is bounded: evicted samples leave
    the window counts, so attainment stays a true fraction of what is
    actually retained."""
    tel = Telemetry(enabled=False)
    eng = SloEngine(SloConfig(objective=0.9, fast_window_s=1000.0,
                              slow_window_s=1000.0, max_samples=8), tel)
    t0 = 10.0
    for i in range(8):
        eng.observe("t", 1e9, ok=False, at_s=t0 + i)    # fill with bad
    for i in range(8):
        eng.observe("t", 1.0, ok=True, at_s=t0 + 8 + i)  # evict them
    assert eng.attainment("t", now=t0 + 16) == 1.0
    assert eng.burn_rates("t", now=t0 + 16) == (0.0, 0.0)


# ---------------------------------------------------------------------------
# burn-rate brownout shaping (supervision.tier_for)
# ---------------------------------------------------------------------------

def test_tier_for_escalates_burning_and_shields_healthy():
    tel = Telemetry(enabled=False)
    pol = BrownoutPolicy(BrownoutConfig(queue_soft=0.5,
                                        queue_heavy=0.75,
                                        queue_critical=0.9), tel)
    eng = SloEngine(SloConfig(objective=0.9, fast_window_s=10.0,
                              slow_window_s=100.0), tel)
    t0 = 100.0
    for i in range(10):
        eng.observe("noisy", 1e9, ok=False, at_s=t0 + i)
        eng.observe("quiet", 1.0, ok=True, at_s=t0 + i)
    now = t0 + 10
    # no engine / no tenant attribution: bit-for-bit the base tier
    assert pol.tier_for("noisy", 6, 10, now, slo=None) \
        == pol.tier(6, 10, now) == 1
    assert pol.tier_for(None, 6, 10, now, slo=eng) == 1
    # a burning tenant escalates to its hint even on an idle queue
    assert eng.tier_hint("noisy", now=now) == 2
    assert pol.tier_for("noisy", 0, 10, now, slo=eng) == 2
    assert pol.tier_for("noisy", 6, 10, now, slo=eng) == 2
    # the healthy tenant is shielded one tier while a neighbor burns:
    # the queue pressure is the noisy tenant's doing, not theirs
    assert pol.tier_for("quiet", 6, 10, now, slo=eng) == 0
    # ... but the device-fault floor is never shielded away
    pol.note_fault(now)
    assert pol.tier_for("quiet", 6, 10, now, slo=eng) == 1


def test_door_burn_rate_brownout_degrades_over_budget_tenant_only():
    """The front-door acceptance bar: with ZERO queue pressure, an
    over-budget tenant's requests are degraded (nfe-capped) purely by
    its burn rate, while a healthy tenant's requests pass untouched."""
    tel = Telemetry(enabled=False)
    (r0, _), = (_replica("r0", tel),)
    door = _door([r0], tel,
                 brownout=BrownoutConfig(queue_soft=5.0, queue_heavy=6.0,
                                         queue_critical=7.0, nfe_cap=4,
                                         force_plan=None),
                 slo=SloConfig(objective=0.9, fast_window_s=30.0,
                               slow_window_s=300.0))
    for _ in range(12):                       # budget exhausted
        door.slo.observe("overbudget", 1e9, ok=False)
    for _ in range(12):                       # inside budget
        door.slo.observe("healthy", 1.0, ok=True)
    assert door.slo.tier_hint("overbudget") == 2
    out_hot = door.submit(SampleRequest(
        resolution=8, diffusion_steps=16, sampler="ddim", seed=1,
        tenant="overbudget")).result(timeout=30)
    out_cold = door.submit(SampleRequest(
        resolution=8, diffusion_steps=16, sampler="ddim", seed=2,
        tenant="healthy")).result(timeout=30)
    door.close()
    assert "nfe_capped" in out_hot.degraded
    assert out_cold.degraded == ()
    snap = tel.registry.snapshot()
    assert snap["slo/burn_fast/overbudget"] >= 4.0
    # delivery feeds the per-replica series SLO routing weighs
    assert snap["slo/attainment/replica:r0"] == 1.0


def test_slo_routing_weight_prefers_unburned_replica():
    """`ReplicaPool.route(weigh=)`: among equally healthy, equally
    loaded replicas, the one whose `replica:<name>` SLO series burns
    is routed AWAY from."""
    tel = Telemetry(enabled=False)
    (r0, _), (r1, _) = _replica("r0", tel), _replica("r1", tel)
    door = _door([r0, r1], tel)
    for _ in range(10):
        door.slo.observe("replica:r0", 1e9, ok=False)
    weigh = door._route_weigh()
    assert weigh(r0) > weigh(r1)
    assert door.pool.route(weigh=weigh).name == "r1"
    door.close()


# ---------------------------------------------------------------------------
# flight recorder (telemetry/flightrec.py)
# ---------------------------------------------------------------------------

class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_flightrec_bundle_contents_and_cross_references(tmp_path):
    clk = _Clock()
    log = EventLog("flightrec-test")
    rec = FlightRecorder(str(tmp_path), clock=clk)
    rec.attach_events(log)
    rec.record({"type": "request_trace", "trace_id": "door-1-0",
                "outcome": "ok"})
    rec.metrics({"frontdoor/requests_ok": 3.0}, step=7)
    clk.t = 1.0
    log.record("replica_lost", "chaos.site", detail="killed r0",
               step=12)
    rec.close()
    paths = rec.incidents
    assert len(paths) == 1 and "replica_lost" in paths[0]
    assert list_incidents(str(tmp_path)) == paths
    with open(paths[0], "r", encoding="utf-8") as f:
        bundle = json.load(f)
    assert bundle["schema_version"] == BUNDLE_SCHEMA_VERSION
    assert bundle["kind"] == "replica_lost"
    assert bundle["incident_id"] == "001-replica_lost"
    assert bundle["detail"] == "chaos.site: killed r0"
    # cross-reference indices: trace ids from the rows, steps from
    # rows + ledger, all three rings captured
    assert bundle["trace_ids"] == ["door-1-0"]
    assert bundle["steps"] == [12]
    assert len(bundle["records"]) == 1
    assert len(bundle["ledger"]) == 1
    assert len(bundle["metric_snapshots"]) == 1
    # closed recorder no longer hears the log
    log.record("replica_lost", "after.close")
    assert len(rec.incidents) == 1


def test_flightrec_cooldown_cap_and_suppression_counting(tmp_path):
    clk = _Clock()
    rec = FlightRecorder(str(tmp_path), cooldown_s=5.0,
                         max_incidents=3, clock=clk)
    assert rec.incident("replica_lost", "a") is not None
    clk.t = 1.0
    assert rec.incident("replica_lost", "b") is None    # cooldown
    clk.t = 2.0
    p = rec.incident("engine_rebuild", "c")     # new kind: not cooled
    assert p is not None
    with open(p, "r", encoding="utf-8") as f:
        # the NEXT bundle of any kind carries the suppression count
        assert json.load(f)["suppressed_since_last"] == 1
    clk.t = 10.0
    assert rec.incident("replica_lost", "d") is not None
    clk.t = 20.0                                # run cap reached
    assert rec.incident("pool_exhausted", "e") is None
    assert len(list_incidents(str(tmp_path))) == 3


def test_flightrec_quarantine_spike_and_row_incidents(tmp_path):
    clk = _Clock()
    log = EventLog("spike-test")
    rec = FlightRecorder(str(tmp_path), quarantine_spike=3,
                         cooldown_s=0.5, clock=clk)
    rec.attach_events(log)
    log.record("quarantine", "data.src", detail="bad record")
    log.record("quarantine", "data.src", detail="bad record")
    assert rec.incidents == []              # routine, not an incident
    log.record("quarantine", "data.src", detail="bad record")
    assert any("quarantine_spike" in p for p in rec.incidents)
    # row-typed incident: an elastic transition arriving as telemetry
    clk.t = 5.0
    rec.record({"type": "elastic_transition", "reason": "scale_down"})
    assert any("elastic_transition" in p for p in rec.incidents)
    rec.close()


def test_hub_wires_flightrec_and_counts_incidents(tmp_path):
    """`Telemetry.create` builds the recorder, forwards rows/exports,
    and subscribes it to the global event log; `close` detaches it."""
    log = EventLog("hub-test")
    with use_event_log(log):
        tel = Telemetry.create(str(tmp_path))
        assert tel.flightrec is not None
        tel.write_record({"type": "request_trace", "trace_id": "x-1",
                          "outcome": "ok"})
        record_event("replica_lost", "chaos.test", detail="r0 down")
        assert tel.registry.snapshot()["telemetry/incidents"] == 1.0
        tel.close()
    paths = list_incidents(str(tmp_path))
    assert len(paths) == 1
    with open(paths[0], "r", encoding="utf-8") as f:
        assert "x-1" in json.load(f)["trace_ids"]
    log.record("replica_lost", "after.close")   # detached: no dump
    assert len(list_incidents(str(tmp_path))) == 1


# ---------------------------------------------------------------------------
# door span <-> histogram reconciliation
# ---------------------------------------------------------------------------

def test_door_span_sums_reconcile_with_latency_histogram(tmp_path):
    """The PR-13 discipline at pool scope: every door trace's phase
    segments tile [submit, delivery] exactly, so the spans summed over
    ALL requests equal the `frontdoor/latency_ms` histogram total."""
    tel = Telemetry.create(str(tmp_path))
    (r0, _), (r1, _) = (_replica("r0", tel, delay=0.02),
                        _replica("r1", tel, delay=0.02))
    door = _door([r0, r1], tel)
    futs = [door.submit(SampleRequest(resolution=8, diffusion_steps=4,
                                      sampler="ddim", seed=40 + i))
            for i in range(4)]
    for f in futs:
        f.result(timeout=30)
    door.close()
    tel.close()
    rows = [json.loads(line) for line in
            (tmp_path / "telemetry.jsonl").read_text().splitlines()]
    door_rows = [r for r in rows if r.get("type") == "request_trace"
                 and r.get("hop") == "door"]
    assert len(door_rows) == 4
    tiled_total = 0.0
    for t in door_rows:
        tiled = sum(ms for name, ms in t["phase_ms"].items()
                    if name != "door.hedge")
        assert tiled == pytest.approx(t["latency_ms"], abs=1e-6)
        tiled_total += tiled
    hist = tel.registry.histogram("frontdoor/latency_ms").snapshot()
    assert hist["count"] == 4
    assert tiled_total == pytest.approx(hist["sum"], abs=1e-6)


# ---------------------------------------------------------------------------
# byte-stable per-tenant SLO artifact (serving/loadgen.py)
# ---------------------------------------------------------------------------

def test_tenant_slo_artifact_byte_stable_and_key_set_pinned(tmp_path):
    from flaxdiff_tpu.serving.loadgen import (TENANT_SLO_FILENAME,
                                              TENANT_SLO_SCHEMA_VERSION,
                                              write_tenant_slo)
    report = {"tenants": {
        "b": {"requests": 4, "completed": 4, "shed": 0, "faulted": 0,
              "errors": 0, "slo_ms": 250.0, "slo_attainment": 0.75,
              "latency_ms": {"p50": 10.123456, "p99": 20.98765}},
        "a": {"requests": 2, "completed": 1, "shed": 1, "faulted": 0,
              "errors": 0, "slo_ms": None, "slo_attainment": 0.5,
              "latency_ms": {"p50": 1.0, "p99": 2.0}},
    }}
    p1 = write_tenant_slo(report, str(tmp_path / "one"))
    p2 = write_tenant_slo(report, str(tmp_path / "two"))
    assert p1.endswith(TENANT_SLO_FILENAME)
    with open(p1, "rb") as f1, open(p2, "rb") as f2:
        b1, b2 = f1.read(), f2.read()
    assert b1 == b2 and b1.endswith(b"\n")      # the contract: bytes
    doc = json.loads(b1)
    assert doc["schema_version"] == TENANT_SLO_SCHEMA_VERSION == 1
    assert list(doc["tenants"]) == ["a", "b"]   # sorted tenants
    assert set(doc["tenants"]["a"]) == {
        "requests", "completed", "shed", "faulted", "errors", "slo_ms",
        "attainment", "p50_ms", "p99_ms"}
    assert doc["tenants"]["b"]["attainment"] == 0.75
    assert doc["tenants"]["b"]["p50_ms"] == 10.123


def test_run_open_loop_writes_artifact_and_feeds_door_slo(tmp_path):
    """The harness tags each tenant's requests, the door's SLO engine
    sees them per tenant, and `artifact_dir` lands the byte-stable
    summary next to the run."""
    from flaxdiff_tpu.serving import (OpenLoopSpec, TenantSpec,
                                      run_open_loop)
    tel = Telemetry(enabled=False)
    (r0, _), = (_replica("r0", tel),)
    door = _door([r0], tel)
    spec = OpenLoopSpec(tenants=(
        TenantSpec(name="t0", n_requests=4, rate_hz=200.0,
                   shape="poisson",
                   mix=({"resolution": 8, "diffusion_steps": 4,
                         "sampler": "ddim"},)),), seed=5)
    rep = run_open_loop(door, spec, workers=2, timeout_s=60,
                        artifact_dir=str(tmp_path))
    door.close()
    assert rep["completed"] == 4
    with open(tmp_path / "tenant_slo.json", "r",
              encoding="utf-8") as f:
        doc = json.load(f)
    assert doc["tenants"]["t0"]["completed"] == 4
    assert doc["tenants"]["t0"]["attainment"] == 1.0
    # tenant attribution reached the ONLINE engine through the door
    assert "slo/attainment/t0" in tel.registry.snapshot()


# ---------------------------------------------------------------------------
# compare_runs: attainment drops + new incidents are regressions
# ---------------------------------------------------------------------------

def _evidence_dir(tmp_path, name, attainment, incident=False):
    d = tmp_path / name
    d.mkdir()
    (d / "tenant_slo.json").write_text(json.dumps(
        {"schema_version": 1, "tenants": {
            "t0": {"requests": 8, "completed": 8, "shed": 0,
                   "faulted": 0, "errors": 0, "slo_ms": 250.0,
                   "attainment": attainment, "p50_ms": 10.0,
                   "p99_ms": 20.0}}}))
    if incident:
        (d / "incident-001-replica_lost.json").write_text(json.dumps(
            {"schema_version": 1, "kind": "replica_lost"}))
    return str(d)


def test_compare_runs_flags_attainment_drop_and_new_incidents(
        tmp_path, capsys):
    from scripts.compare_runs import main
    a = _evidence_dir(tmp_path, "a", 1.0)
    b = _evidence_dir(tmp_path, "b", 0.5, incident=True)
    assert main([a, b, "--json"]) == 1
    doc = json.loads(capsys.readouterr().out)
    regs = {(r["stage"], r["metric"]) for r in doc["regressions"]}
    assert ("tenant_slo", "t0/attainment") in regs   # down = worse
    # a bundle appearing from a ZERO base is a regression — count
    # semantics, not relative thresholds
    assert ("incidents", "incidents/total") in regs
    # the reverse direction is an improvement, not a regression
    assert main([b, a, "--json"]) == 0
    capsys.readouterr()
    # text mode names the finding
    assert main([a, b]) == 1
    out = capsys.readouterr().out
    assert "REGRESSION" in out and "attainment" in out


def _devprof_dir(tmp_path, name, attn_ms, mfu):
    d = tmp_path / name
    d.mkdir()
    row = {"type": "devprof", "status": "ok", "source": "device",
           "capture": "t.trace.json.gz", "step": 8, "steps": 4,
           "device_total_ms": (attn_ms + 1.0) * 4,
           "device_ms_per_step": attn_ms + 1.0,
           "collective_ms": 0.5, "collective_count": 2,
           "compute_ms": attn_ms, "layout_copy_ms": 0.1,
           "layout_copy_count": 1, "fusion_gap_ms": 0.2,
           "fusion_gap_count": 1, "measured_mfu": mfu,
           "families": {"attn": {"ms": attn_ms, "count": 4}}}
    (d / "devprof.jsonl").write_text(json.dumps(row) + "\n")
    return str(d)


def test_compare_runs_devprof_direction_contract(tmp_path, capsys):
    """Contract (ISSUE 19): op-family device ms regress UP, measured
    MFU regresses DOWN, op counts are neutral program-shape facts."""
    from scripts.compare_runs import main
    a = _devprof_dir(tmp_path, "a", attn_ms=4.0, mfu=0.4)
    b = _devprof_dir(tmp_path, "b", attn_ms=8.0, mfu=0.2)
    assert main([a, b, "--json"]) == 1
    doc = json.loads(capsys.readouterr().out)
    regs = {(r["stage"], r["metric"]) for r in doc["regressions"]}
    assert ("devprof", "devprof/families/attn_ms") in regs
    assert ("devprof", "devprof/measured_mfu") in regs
    rows = {r["metric"]: r for r in doc["stages"]["devprof"]["rows"]}
    assert rows["devprof/measured_mfu"]["direction"] == "down_is_worse"
    assert rows["devprof/device_ms_per_step"]["direction"] \
        == "up_is_worse"
    assert rows["devprof/families/attn_count"]["direction"] == "info"
    assert rows["devprof/collective_count"]["direction"] == "info"
    # the same deltas in the other direction are improvements
    assert main([b, a, "--json"]) == 0


# ---------------------------------------------------------------------------
# diagnose_run: schema_version pin + SLO / Incidents sections
# ---------------------------------------------------------------------------

def test_diagnose_json_schema_pinned_and_incident_sections(
        tmp_path, capsys):
    """Regression pin (ISSUE 18): the --json report carries
    `schema_version` and EXACTLY this top-level key set — consumers
    parse it blind, so a key appearing or vanishing is a contract
    change, not a refactor."""
    from scripts.diagnose_run import REPORT_SCHEMA_VERSION, main
    log = EventLog("diagnose-test")
    with use_event_log(log):
        tel = Telemetry.create(str(tmp_path))
        tel.write_record({"type": "request_trace",
                          "trace_id": "door-1-0", "outcome": "ok",
                          "queue_ms": 1.0, "compile_ms": 2.0,
                          "device_ms": 3.0, "latency_ms": 6.0,
                          "sampler": "ddim", "nfe": 4,
                          "resolution": 8})
        tel.registry.gauge("slo/attainment/t0").set(0.5)
        tel.registry.gauge("slo/burn_fast/t0").set(5.0)
        tel.registry.gauge("slo/burn_slow/t0").set(2.0)
        tel.export(step=1)
        tel.flightrec.incident("replica_lost", "test kill r0", step=3)
        tel.close()

    assert main([str(tmp_path), "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["schema_version"] == REPORT_SCHEMA_VERSION == 3
    assert set(doc) == {"schema_version", "goodput", "steps",
                        "phase_rows", "step_wall_s", "pod_last",
                        "health", "elasticity", "frontdoor", "slo",
                        "incidents", "data_health", "request_traces",
                        "programs", "device_profile", "plan"}
    # no profile windows ran: the stanza is present but empty (the
    # key set is the contract, not conditional)
    assert doc["device_profile"] == {"windows": 0,
                                     "parse_failures": 0,
                                     "last": None}
    # same contract for the planner stanza: present, empty without
    # any committed plan decision
    assert doc["plan"] == {"decisions": []}
    assert doc["slo"]["slo/attainment/t0"] == 0.5
    assert len(doc["incidents"]) == 1
    inc = doc["incidents"][0]
    assert inc["kind"] == "replica_lost" and inc["step"] == 3
    assert inc["records"] >= 1 and inc["trace_ids"] >= 1

    assert main([str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "== SLO budgets" in out and "<- BURNING" in out
    assert "== Incidents (1 bundle(s)) ==" in out
    assert "001-replica_lost" in out and "test kill r0" in out

"""Ring attention: exact sequence-parallel attention over a mesh axis.

The reference has NO sequence parallelism of any kind (SURVEY.md §5.7);
this is the TPU-native extension that lifts the single-device sequence
bound. Algorithm (Liu et al. 2023, Ring Attention with Blockwise
Transformers): each device holds one sequence shard of Q and of K/V; K/V
shards rotate around the ring via `jax.lax.ppermute` while every device
accumulates its Q-shard's attention with the numerically-stable online
softmax (running max / running sum), so the full [S, S] score matrix is
never materialized and communication overlaps compute on the ICI ring.

Exactness: the result equals full softmax attention over the complete
sequence (verified against the XLA path in tests/test_ring_attention.py).
"""
from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map


def _online_block(carry, kv_block, q, scale):
    """Accumulate one K/V block into the (out, running_sum, running_max)
    online-softmax carry. Shapes: q [B, Sq, H, D]; k/v [B, Skv, H, D];
    carry o [B, Sq, H, D], l [B, H, Sq], m [B, H, Sq]."""
    o, l, m = carry
    k, v = kv_block
    # scores in f32 for a stable softmax regardless of compute dtype
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    m_blk = jnp.max(s, axis=-1)                        # [B, H, Sq]
    m_new = jnp.maximum(m, m_blk)
    p = jnp.exp(s - m_new[..., None])                  # [B, H, Sq, Skv]
    corr = jnp.exp(m - m_new)                          # [B, H, Sq]
    l_new = l * corr + jnp.sum(p, axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32),
                    preferred_element_type=jnp.float32)
    o_new = o * corr.transpose(0, 2, 1)[..., None] + pv
    return o_new, l_new, m_new


def ring_attention_sharded(q: jax.Array, k: jax.Array, v: jax.Array,
                           axis_name: str, scale: Optional[float] = None
                           ) -> jax.Array:
    """Body to be called INSIDE shard_map: q/k/v are the local sequence
    shards [B, S_local, H, D]; the sequence axis is sharded over
    `axis_name`. Returns the local shard of the attention output."""
    B, Sq, H, D = q.shape
    scale = scale if scale is not None else D ** -0.5
    n = jax.lax.psum(1, axis_name)

    # Derive the zero-init carry from q so it inherits q's full set of
    # device-varying axes (shard_map's varying-axis checker requires the
    # fori_loop carry type to match the accumulator outputs exactly).
    o = (q * 0).astype(jnp.float32)                       # [B, Sq, H, D]
    l = jnp.sum(o, axis=-1).transpose(0, 2, 1)            # [B, H, Sq]
    m = l - jnp.inf

    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(i, state):
        o, l, m, k_cur, v_cur = state
        o, l, m = _online_block((o, l, m), (k_cur, v_cur), q, scale)
        # rotate K/V one hop around the ring; the last rotation is wasted
        # but keeps the loop body uniform (static unrolled by scan).
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return o, l, m, k_nxt, v_nxt

    o, l, m, _, _ = jax.lax.fori_loop(0, n, step, (o, l, m, k, v))
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def seq_shard_spec(mesh: Mesh, seq_axis: str = "seq",
                   batch_axes: Tuple[str, ...] = ("data",)) -> P:
    """PartitionSpec for [B, S, H, D] with S on the seq axis (shared by
    the ring and Ulysses shard_map wrappers)."""
    b_spec = tuple(a for a in batch_axes if a in mesh.axis_names)
    b = b_spec if len(b_spec) != 1 else b_spec[0]
    return P(b if b_spec else None, seq_axis, None, None)


def ring_self_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                        mesh: Mesh, seq_axis: str = "seq",
                        batch_axes: Tuple[str, ...] = ("data",),
                        scale: Optional[float] = None) -> jax.Array:
    """Top-level entry: [B, S, H, D] arrays, S sharded over `seq_axis`,
    B over `batch_axes`. Wraps `ring_attention_sharded` in shard_map so
    XLA SPMD emits the ppermute ring over ICI."""
    spec = seq_shard_spec(mesh, seq_axis, batch_axes)
    fn = shard_map(
        functools.partial(ring_attention_sharded, axis_name=seq_axis,
                          scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)


def sequence_sharding(mesh: Mesh, seq_axis: str = "seq",
                      batch_axes: Tuple[str, ...] = ("data",)
                      ) -> NamedSharding:
    """NamedSharding for [B, S, ...] activations with S on the seq axis."""
    b_spec = tuple(a for a in batch_axes if a in mesh.axis_names)
    b = b_spec if len(b_spec) != 1 else b_spec[0]
    return NamedSharding(mesh, P(b if b_spec else None, seq_axis))

"""First-party Pallas TPU flash attention (online-softmax, O(N) memory).

Replaces the reference's dependency on JAX's prebuilt kernel
(reference flaxdiff/models/attention.py:14-17,100-102). Design:

- grid = (batch*heads, q_blocks); each program holds one q block in VMEM
  and streams k/v blocks with a fori_loop carrying running (max, sum, acc)
  in f32 — the classic online softmax, never materializing [Lq, Lk] in HBM.
- kv length is masked via iota so cross-attention (e.g. CLIP kv_len=77)
  works after padding to the lane-aligned block.
- backward: custom_vjp recomputes attention with the XLA path and reuses
  its VJP — correct gradients, flash-memory forward. A dedicated backward
  kernel is a later optimization.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, scale: float, block_k: int,
                  kv_len: int):
    q = q_ref[0].astype(jnp.float32)  # [block_q, d]
    block_q, d = q.shape
    padded_kv = k_ref.shape[1]
    num_kb = padded_kv // block_k

    def body(i, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [block_q, block_k]
        kv_idx = i * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(kv_idx < kv_len, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)
    _, l, acc = jax.lax.fori_loop(0, num_kb, body, (m0, l0, acc0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _pad_to(x: jax.Array, axis: int, multiple: int) -> jax.Array:
    size = x.shape[axis]
    pad = (-size) % multiple
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _flash_fwd_impl(q: jax.Array, k: jax.Array, v: jax.Array,
                    scale: Optional[float], block_q: int = 128,
                    block_k: int = 128, interpret: bool = False) -> jax.Array:
    """q,k,v: [B, L, H, D] -> [B, Lq, H, D]."""
    b, lq, h, d = q.shape
    kv_len = k.shape[1]
    if scale is None:
        scale = 1.0 / (d ** 0.5)

    # [B, L, H, D] -> [B*H, L, D]
    def to_bh(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, x.shape[1], d)

    qb, kb, vb = to_bh(q), to_bh(k), to_bh(v)
    block_q_eff = min(block_q, max(lq, 8))
    qb = _pad_to(qb, 1, block_q_eff)
    block_k_eff = min(block_k, max(kv_len, 8))
    kb = _pad_to(kb, 1, block_k_eff)
    vb = _pad_to(vb, 1, block_k_eff)
    lq_pad, lk_pad = qb.shape[1], kb.shape[1]

    out = pl.pallas_call(
        functools.partial(_flash_kernel, scale=scale, block_k=block_k_eff,
                          kv_len=kv_len),
        grid=(b * h, lq_pad // block_q_eff),
        in_specs=[
            pl.BlockSpec((1, block_q_eff, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, lk_pad, d), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, lk_pad, d), lambda bh, qi: (bh, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q_eff, d), lambda bh, qi: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, lq_pad, d), q.dtype),
        interpret=interpret,
    )(qb, kb, vb)

    out = out[:, :lq, :].reshape(b, h, lq, d).transpose(0, 2, 1, 3)
    return out


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    scale: Optional[float] = None, block_q: int = 128,
                    block_k: int = 128, interpret: bool = False) -> jax.Array:
    return _flash_fwd_impl(q, k, v, scale, block_q, block_k, interpret)


def _fwd(q, k, v, scale, block_q, block_k, interpret):
    return _flash_fwd_impl(q, k, v, scale, block_q, block_k, interpret), (q, k, v)


def _bwd(scale, block_q, block_k, interpret, res, g):
    q, k, v = res
    from .attention import _xla_attention
    _, vjp = jax.vjp(lambda q_, k_, v_: _xla_attention(q_, k_, v_, scale=scale), q, k, v)
    return vjp(g)


flash_attention.defvjp(_fwd, _bwd)

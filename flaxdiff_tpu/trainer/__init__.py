"""Trainer layer: FSDP-sharded diffusion training.

Capability parity with the reference trainer hierarchy (SimpleTrainer ->
DiffusionTrainer -> GeneralDiffusionTrainer, flaxdiff/trainer/*), built
TPU-first: one `jax.jit` train step over NamedSharding (params + optimizer
state sharded on the `fsdp` axis, batch on `data`), donated state, EMA as
a sharded pytree update, CFG dropout by `jnp.where` null-embedding mask,
and no per-step host sync (loss is read back only at the log cadence).
"""
from .autoencoder_trainer import AutoEncoderTrainer, AutoEncoderTrainerConfig
from .checkpoints import Checkpointer, abstract_state_like
from .logging import (
    JsonlLogger,
    MultiLogger,
    WandbLogger,
    attach_resilience,
    make_logger,
    save_image_grid,
)
from .optim import flat_optimizer
from .registry import ModelRegistry
from .train_state import TrainState
from .train_step import TrainStepConfig, make_train_step
from .trainer import DiffusionTrainer, TrainerConfig
from .validation import ValidationConfig, Validator

__all__ = [
    "TrainState",
    "flat_optimizer",
    "TrainStepConfig",
    "make_train_step",
    "DiffusionTrainer",
    "TrainerConfig",
    "Checkpointer",
    "abstract_state_like",
    "ValidationConfig",
    "Validator",
    "JsonlLogger",
    "WandbLogger",
    "MultiLogger",
    "make_logger",
    "attach_resilience",
    "save_image_grid",
    "ModelRegistry",
    "AutoEncoderTrainer",
    "AutoEncoderTrainerConfig",
]

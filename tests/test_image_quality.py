"""PSNR/SSIM metric tests (the reference's psnr.py/ssim.py are empty files)."""
import numpy as np
import pytest

from flaxdiff_tpu.metrics import (get_psnr_metric, get_ssim_metric, psnr,
                                  ssim)


def test_psnr_identity_is_large(rng):
    x = rng.uniform(-1, 1, size=(2, 32, 32, 3)).astype(np.float32)
    assert float(psnr(x, x)) > 100.0


def test_psnr_known_value():
    # uniform error of 0.5 on range 2.0: psnr = 20*log10(2/0.5) = 12.04 dB
    x = np.zeros((1, 16, 16, 3), np.float32)
    y = np.full_like(x, 0.5)
    np.testing.assert_allclose(float(psnr(x, y)), 20 * np.log10(4.0),
                               rtol=1e-5)


def test_psnr_monotonic_in_noise(rng):
    x = rng.uniform(-1, 1, size=(2, 32, 32, 3)).astype(np.float32)
    small = x + rng.normal(0, 0.01, x.shape).astype(np.float32)
    big = x + rng.normal(0, 0.2, x.shape).astype(np.float32)
    assert float(psnr(x, small)) > float(psnr(x, big))


def test_ssim_identity_is_one(rng):
    x = rng.uniform(-1, 1, size=(2, 24, 24, 3)).astype(np.float32)
    np.testing.assert_allclose(float(ssim(x, x)), 1.0, atol=1e-5)


def test_ssim_uncorrelated_near_zero(rng):
    x = rng.normal(size=(2, 32, 32, 1)).astype(np.float32)
    y = rng.normal(size=(2, 32, 32, 1)).astype(np.float32)
    assert abs(float(ssim(x, y))) < 0.2


def test_ssim_degrades_with_noise(rng):
    x = rng.uniform(-1, 1, size=(2, 32, 32, 3)).astype(np.float32)
    noisy = x + rng.normal(0, 0.3, x.shape).astype(np.float32)
    s = float(ssim(x, noisy))
    assert 0.0 < s < 0.95


def test_ssim_video_shape(rng):
    x = rng.uniform(-1, 1, size=(2, 3, 16, 16, 3)).astype(np.float32)
    np.testing.assert_allclose(float(ssim(x, x)), 1.0, atol=1e-5)
    assert float(psnr(x, x)) > 100.0


def test_ssim_window_too_large_raises(rng):
    x = rng.uniform(-1, 1, size=(1, 8, 8, 3)).astype(np.float32)
    with pytest.raises(ValueError, match="smaller than"):
        ssim(x, x)


def test_metric_factories_pair_against_batch(rng):
    x = rng.uniform(-1, 1, size=(4, 16, 16, 3)).astype(np.float32)
    batch = {"sample": x}
    noisy = (x + rng.normal(0, 0.1, x.shape)).astype(np.float32)
    m_psnr, m_ssim = get_psnr_metric(), get_ssim_metric()
    assert m_psnr.higher_is_better and m_ssim.higher_is_better
    p = m_psnr.function(noisy, batch)
    s = m_ssim.function(noisy, batch)
    assert 5.0 < p < 40.0
    assert 0.0 < s < 1.0
    # generated batch larger than the paired batch: scores the paired prefix
    assert m_psnr.function(np.concatenate([noisy, noisy]), batch) == p


def test_metric_factories_bright_samples_vs_uint8_batch(rng):
    """A bright sample batch (no pixel below 0) must still be mapped by
    the fixed [-1,1]->[0,1] contract, not a value heuristic: scored
    against its own uint8 rendering, PSNR is near-lossless."""
    pred = rng.uniform(0.2, 1.0, size=(2, 16, 16, 3)).astype(np.float32)
    target_u8 = np.round((pred + 1.0) / 2.0 * 255.0).astype(np.uint8)
    p = get_psnr_metric().function(pred, {"sample": target_u8})
    assert p > 40.0, p   # only uint8 quantization error remains
    s = get_ssim_metric().function(pred, {"sample": target_u8})
    assert s > 0.98, s


def test_metric_factories_require_paired_batch(rng):
    x = rng.uniform(-1, 1, size=(2, 16, 16, 3)).astype(np.float32)
    with pytest.raises(ValueError, match="paired batch"):
        get_psnr_metric().function(x, None)


def test_autoencoder_trainer_evaluate(rng, mesh):
    import jax
    import optax

    from flaxdiff_tpu.models.autoencoder import KLAutoEncoder
    from flaxdiff_tpu.trainer.autoencoder_trainer import (
        AutoEncoderTrainer, AutoEncoderTrainerConfig)

    vae = KLAutoEncoder.create(
        jax.random.PRNGKey(0), input_channels=3, image_size=16,
        latent_channels=2, block_channels=(8, 16), layers_per_block=1,
        norm_groups=4)
    trainer = AutoEncoderTrainer(
        vae, optax.adam(1e-3), mesh,
        AutoEncoderTrainerConfig(log_every=10, normalize=False))
    batch = {"sample": rng.uniform(-1, 1, size=(8, 16, 16, 3))
             .astype(np.float32)}
    out = trainer.evaluate(batch)
    assert np.isfinite(out["psnr"])
    assert "ssim" in out and -1.0 <= out["ssim"] <= 1.0

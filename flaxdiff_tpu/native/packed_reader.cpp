// Packed-record file reader: mmap-backed, zero-copy random access.
//
// First-party native replacement for the role grain's C++ ArrayRecord
// reader plays in the reference (data/sources/images.py:242
// pygrain.ArrayRecordDataSource): the data layer's hot read path stays
// out of the Python interpreter. Exposed to Python via ctypes
// (flaxdiff_tpu/native/__init__.py).
//
// File layout (little-endian):
//   [0:4)   magic "FDTR"
//   [4:8)   u32 version (1)
//   [8:16)  u64 num_records
//   [16:16+16*n) index: n * (u64 offset, u64 length), offsets relative
//                 to payload start (16 + 16*n)
//   [...]   payload bytes
#include <cstdint>
#include <cstring>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr char kMagic[4] = {'F', 'D', 'T', 'R'};

struct IndexEntry {
  uint64_t offset;
  uint64_t length;
};

struct Reader {
  int fd = -1;
  const uint8_t* map = nullptr;
  size_t map_size = 0;
  uint64_t num_records = 0;
  const IndexEntry* index = nullptr;
  const uint8_t* payload = nullptr;
  size_t payload_size = 0;
};

}  // namespace

extern "C" {

// Returns an opaque handle or nullptr on failure.
void* pr_open(const char* path) {
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (::fstat(fd, &st) != 0 || st.st_size < 16) {
    ::close(fd);
    return nullptr;
  }
  void* map = ::mmap(nullptr, st.st_size, PROT_READ, MAP_PRIVATE, fd, 0);
  if (map == MAP_FAILED) {
    ::close(fd);
    return nullptr;
  }
  const uint8_t* base = static_cast<const uint8_t*>(map);
  if (std::memcmp(base, kMagic, 4) != 0) {
    ::munmap(map, st.st_size);
    ::close(fd);
    return nullptr;
  }
  uint32_t version;
  std::memcpy(&version, base + 4, 4);
  if (version != 1) {
    ::munmap(map, st.st_size);
    ::close(fd);
    return nullptr;
  }
  uint64_t n;
  std::memcpy(&n, base + 8, 8);
  const size_t header = 16 + 16 * static_cast<size_t>(n);
  if (static_cast<size_t>(st.st_size) < header) {
    ::munmap(map, st.st_size);
    ::close(fd);
    return nullptr;
  }
  Reader* r = new Reader;
  r->fd = fd;
  r->map = base;
  r->map_size = st.st_size;
  r->num_records = n;
  r->index = reinterpret_cast<const IndexEntry*>(base + 16);
  r->payload = base + header;
  r->payload_size = st.st_size - header;
  // Validate the index once at open so per-record reads skip bounds work.
  for (uint64_t i = 0; i < n; ++i) {
    const IndexEntry& e = r->index[i];
    if (e.offset > r->payload_size || e.length > r->payload_size - e.offset) {
      delete r;
      ::munmap(map, st.st_size);
      ::close(fd);
      return nullptr;
    }
  }
  return r;
}

uint64_t pr_num_records(void* handle) {
  return handle ? static_cast<Reader*>(handle)->num_records : 0;
}

uint64_t pr_record_length(void* handle, uint64_t idx) {
  Reader* r = static_cast<Reader*>(handle);
  if (!r || idx >= r->num_records) return 0;
  return r->index[idx].length;
}

// Zero-copy pointer into the mapping (valid until pr_close).
const void* pr_record_ptr(void* handle, uint64_t idx) {
  Reader* r = static_cast<Reader*>(handle);
  if (!r || idx >= r->num_records) return nullptr;
  return r->payload + r->index[idx].offset;
}

// Copying read for callers that want an owned buffer. Returns bytes
// written, or 0 on error / insufficient buffer.
uint64_t pr_read_record(void* handle, uint64_t idx, void* buf,
                        uint64_t buf_len) {
  Reader* r = static_cast<Reader*>(handle);
  if (!r || idx >= r->num_records) return 0;
  const IndexEntry& e = r->index[idx];
  if (buf_len < e.length) return 0;
  std::memcpy(buf, r->payload + e.offset, e.length);
  return e.length;
}

void pr_close(void* handle) {
  Reader* r = static_cast<Reader*>(handle);
  if (!r) return;
  if (r->map) ::munmap(const_cast<uint8_t*>(r->map), r->map_size);
  if (r->fd >= 0) ::close(r->fd);
  delete r;
}

}  // extern "C"

"""Heartbeat watchdog: detects a stalled train step or wedged data
loader and converts an opaque hang into a structured event + clean
checkpoint-and-exit.

The monitored loop calls `beat()` once per iteration; a daemon thread
checks the gap between beats. On a stall it records a `watchdog_stall`
event and runs `on_stall(gap_seconds)` exactly once per stall episode
(re-arming when beats resume). The trainer's default action raises
SIGTERM against its own process, which lands in the existing
preemption path (`TrainerConfig.checkpoint_on_sigterm`): finish/abandon
the step, checkpoint, return cleanly — the same guarantee a pod
eviction gets. SIGTERM (not an in-thread exception) because a truly
wedged main thread can only be pre-empted at a signal delivery point;
Python delivers signals even inside `queue.get`/`time.sleep` waits.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from .events import EventLog, global_event_log


class Watchdog:
    """Daemon-thread heartbeat monitor.

    timeout:  max seconds between beats before the stall action fires.
    on_stall: callback(gap_seconds); called once per stall episode from
              the watchdog thread. Exceptions are swallowed (recorded).
    site:     event-log site label, e.g. "train.step".
    poll:     check cadence; defaults to timeout/4 clamped to [0.05, 1].
    """

    def __init__(self, timeout: float,
                 on_stall: Optional[Callable[[float], None]] = None,
                 site: str = "train.step",
                 poll: Optional[float] = None,
                 event_log: Optional[EventLog] = None,
                 clock: Callable[[], float] = time.monotonic):
        if timeout <= 0:
            raise ValueError("watchdog timeout must be > 0")
        self.timeout = timeout
        self.site = site
        self.on_stall = on_stall
        self.poll = poll if poll is not None else min(max(timeout / 4, 0.05),
                                                      1.0)
        self._events = event_log if event_log is not None \
            else global_event_log()
        self._clock = clock
        self._lock = threading.Lock()
        self._last_beat = clock()
        self._paused = 0
        self._fired_this_episode = False
        self.stall_count = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- heartbeat -----------------------------------------------------------
    def beat(self) -> None:
        with self._lock:
            self._last_beat = self._clock()
            self._fired_this_episode = False      # re-arm after recovery

    def pause(self) -> None:
        """Suspend stall detection (e.g. around a known-long compile)."""
        with self._lock:
            self._paused += 1

    def resume(self) -> None:
        with self._lock:
            self._paused = max(self._paused - 1, 0)
            self._last_beat = self._clock()

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "Watchdog":
        if self._thread is not None:
            return self
        self.beat()
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"flaxdiff-watchdog-{self.site}")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=self.poll * 4 + 1.0)
        self._thread = None

    def __enter__(self) -> "Watchdog":
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- monitor thread ------------------------------------------------------
    def _run(self) -> None:
        while not self._stop.wait(self.poll):
            with self._lock:
                if self._paused or self._fired_this_episode:
                    continue
                gap = self._clock() - self._last_beat
                stalled = gap > self.timeout
                if stalled:
                    self._fired_this_episode = True
                    self.stall_count += 1
            if stalled:
                self._events.record(
                    "watchdog_stall", self.site,
                    detail=f"no heartbeat for {gap:.2f}s "
                           f"(timeout {self.timeout}s)")
                if self.on_stall is not None:
                    try:
                        self.on_stall(gap)
                    except Exception:
                        from .events import log
                        log.exception("watchdog on_stall action failed")

#!/bin/bash
# Download an image-text corpus with img2dataset and pack it into this
# framework's sharded packed-record format (data/sharded_source.py).
#
# Operational analogue of the reference's corpus downloaders
# (reference datasets/cc12m downloader.sh, custom datasets
# downloader.sh) with one deliberate difference: instead of emitting
# ArrayRecord straight to GCS, we download webdataset shards locally
# (or to a mounted bucket — see mount_gcs.sh) and pack them with
# scripts/pack_dataset.py, whose output the native C++ reader and the
# grain ShardedPackedSource consume directly.
#
# Usage:
#   scripts/datasets/download_corpus.sh URL_LIST OUTPUT_DIR [IMAGE_SIZE]
#
#   URL_LIST    tsv/parquet of (url, caption) pairs, e.g. cc12m.tsv
#   OUTPUT_DIR  where webdataset shards + packed shards land
#   IMAGE_SIZE  resize target (default 256)
#
# Requires: pip install img2dataset  (not bundled with the framework)
set -euo pipefail

URL_LIST=${1:?usage: download_corpus.sh URL_LIST OUTPUT_DIR [IMAGE_SIZE]}
OUT=${2:?usage: download_corpus.sh URL_LIST OUTPUT_DIR [IMAGE_SIZE]}
SIZE=${3:-256}

case "$URL_LIST" in
  *.tsv)  FORMAT=tsv; URL_COL=image_url; CAP_COL=caption ;;
  *.parquet) FORMAT=parquet; URL_COL=url; CAP_COL=caption ;;
  *) echo "unsupported url list format: $URL_LIST" >&2; exit 1 ;;
esac

mkdir -p "$OUT/webdataset" "$OUT/packed"

img2dataset \
  --url_list "$URL_LIST" --input_format "$FORMAT" \
  --url_col "$URL_COL" --caption_col "$CAP_COL" \
  --output_format webdataset --output_folder "$OUT/webdataset" \
  --image_size "$SIZE" --min_image_size 100 --max_aspect_ratio 2.4 \
  --processes_count "$(nproc)" --thread_count 64 \
  --number_sample_per_shard 50000 \
  --compute_hash None --max_shard_retry 3 --timeout 60

# Pack the webdataset shards into packed-record shards; the resulting
# directory is loadable as `--dataset packed_shards:<OUT>/packed`.
python "$(dirname "$0")/../pack_dataset.py" \
  --src "$OUT/webdataset" --out "$OUT/packed" --shards 16

echo "packed corpus ready: $OUT/packed"

"""Batched sampler scheduler: thread-safe admission, micro-batch
rounds with continuous admission, bounded in-flight dispatch, deadline
shedding, and per-request SLO telemetry.

Architecture (docs/SERVING.md):

- **submit()** enqueues a `SampleRequest` and returns a `ServingFuture`
  immediately. Overload is shed at the door (`max_queue`), deadlines
  are shed at dispatch time — both *before* any compute is spent,
  counted at `serving/shed`.
- A single **dispatch loop** drains the queue in rounds. Each round
  serves one compatibility group (least-recently-served for fairness),
  admits queued requests into the group's free capacity, pads the
  batch to a bucket, and advances every row by up to
  `round_steps` of its OWN trajectory through the engine's compiled
  program. Rows that complete exit mid-group ("continuous admission"):
  a 10-NFE request batched with a 50-NFE one returns after its own
  rounds, and its slot is refilled from the queue.
- Completed rows are handed (still device-resident, dispatch still
  async) to a **completion thread** that performs the only host syncs
  — `_block_until_ready` + `_device_get`, module-level seams so tests
  can count them, the PR-5 sync-free-loop convention. The dispatch
  loop keeps at most `max_inflight` completed batches in flight;
  beyond that it waits (genuine backpressure, counted at
  `serving/backpressure_waits`) instead of racing the device.
- **close(drain=True)** stops admission, finishes queued + active
  work, and joins both threads.
"""
from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

from ..telemetry.reqtrace import RequestTracer
from .engine import (DEFAULT_BATCH_BUCKETS, RequestState,
                     SamplerProgramEngine, bucket_up, nfe_bucket)
from .request import (DeadlineExceeded, SampleRequest, SampleResult,
                      SchedulerClosed, ServingFuture)

# Millisecond-scale SLO latency buckets (the registry default bounds
# are seconds-scale training phases).
MS_BUCKET_BOUNDS: Tuple[float, ...] = (
    1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
    1000.0, 2500.0, 5000.0, 10000.0, 30000.0, 60000.0, 120000.0,
    300000.0)


# The scheduler's host-sync + clock primitives, module-level so tests
# can monkeypatch counting wrappers (the PR-5 seam convention): the
# dispatch loop itself must never block on device work.

def _block_until_ready(x) -> None:
    import jax
    jax.block_until_ready(x)


def _device_get(x):
    import jax
    import numpy as np
    return np.asarray(jax.device_get(x))


def _now() -> float:
    return time.perf_counter()


@dataclasses.dataclass
class SchedulerConfig:
    """Knobs for the dispatch loop.

    round_steps: trajectory steps advanced per round (the compiled
      program's scan length). 0 = run-to-completion: one round runs a
      group's whole (power-of-two-bucketed) max NFE — lowest overhead,
      but a short request then waits for the longest row in its round.
    batch_buckets: padded batch sizes; max(batch_buckets) caps rows
      per round.
    max_queue: admission cap; submits past it are shed at the door.
    max_inflight: completed batches allowed in flight to the
      completion thread before the dispatch loop backpressures.
    """
    round_steps: int = 8
    batch_buckets: Tuple[int, ...] = DEFAULT_BATCH_BUCKETS
    max_queue: int = 256
    max_inflight: int = 2
    drain_timeout_s: float = 120.0


class ServingScheduler:
    """Thread-safe request scheduler over a `SamplerProgramEngine`.

    Pass `autostart=False` to submit requests before the first round
    (tests use this to pin grouping deterministically), then `start()`.
    """

    def __init__(self, pipeline=None, engine=None,
                 config: Optional[SchedulerConfig] = None,
                 telemetry=None, autostart: bool = True):
        if engine is None:
            if pipeline is None:
                raise ValueError("need a pipeline or an engine")
            engine = SamplerProgramEngine(pipeline, telemetry=telemetry)
        if telemetry is None:
            from ..telemetry import global_telemetry
            telemetry = global_telemetry()
        self.engine = engine
        self.config = config or SchedulerConfig()
        self.telemetry = telemetry
        # request-scoped tracing (telemetry/reqtrace.py): every call is
        # a no-op on a hub without a trace recorder, and a traced run
        # performs the IDENTICAL seam-counted host syncs as an untraced
        # one (counting-mock tested) — tracing is host bookkeeping only
        self.tracer = RequestTracer(telemetry)

        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        # queue entries: (request, future, submit_time, trace-or-None)
        self._queue: Deque[Tuple[SampleRequest, ServingFuture, float,
                                 object]] = deque()
        self._active: Dict[tuple, List[RequestState]] = {}
        self._completions: Deque[Tuple[List[RequestState], object, float]] \
            = deque()
        self._last_served: Dict[tuple, int] = {}
        self._round_no = 0
        self._closed = False
        self._draining = False
        self._dispatch_done = False

        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, name="serving-dispatch",
            daemon=True)
        self._completer = threading.Thread(
            target=self._completion_loop, name="serving-complete",
            daemon=True)
        self._started = False
        if autostart:
            self.start()

    # -- lifecycle ------------------------------------------------------------
    def prewarm(self, reqs: List[SampleRequest]) -> Dict[str, float]:
        """Startup hook: compile the compiled-program tuples the given
        traffic prototypes will hit — every (bucket, NFE, plan) under
        this scheduler's `round_steps`/`batch_buckets` config — BEFORE
        admission opens, so cold p50 never hits user traffic. Call
        before (or after) `start()`, but before submitting; delegates
        to `SamplerProgramEngine.prewarm`."""
        return self.engine.prewarm(reqs, self.config.round_steps,
                                   self.config.batch_buckets)

    def start(self) -> "ServingScheduler":
        if not self._started:
            self._started = True
            self._dispatcher.start()
            self._completer.start()
        return self

    def __enter__(self) -> "ServingScheduler":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close(drain=exc == (None, None, None))

    def close(self, drain: bool = True,
              timeout: Optional[float] = None) -> None:
        """Stop admission; with drain, finish queued + active work
        first. Idempotent."""
        timeout = self.config.drain_timeout_s if timeout is None else timeout
        with self._cv:
            self._closed = True
            self._draining = drain
            if not drain or not self._started:
                # nothing will ever drain an unstarted scheduler —
                # resolve pending futures instead of leaving waiters
                # hanging
                for _, fut, _, _ in self._queue:
                    fut.set_exception(SchedulerClosed("scheduler closed"))
                self._queue.clear()
                for rows in self._active.values():
                    for r in rows:
                        r.future.set_exception(
                            SchedulerClosed("scheduler closed"))
                self._active.clear()
            self._cv.notify_all()
        if self._started:
            self._dispatcher.join(timeout)
        with self._cv:
            self._dispatch_done = True
            self._cv.notify_all()
        if self._started:
            self._completer.join(timeout)

    # -- admission ------------------------------------------------------------
    def submit(self, req: SampleRequest) -> ServingFuture:
        """Enqueue one request. Never blocks: overload and post-close
        submits come back as exceptions on the returned future."""
        fut = ServingFuture()
        tel = self.telemetry
        with self._cv:
            if self._closed:
                fut.set_exception(SchedulerClosed("scheduler closed"))
                return fut
            tel.counter("serving/requests_in").inc()
            t_sub = _now()
            tr = self.tracer.begin(req, t_sub)   # None on disabled hub
            if len(self._queue) >= self.config.max_queue:
                tel.counter("serving/shed").inc()
                self.tracer.shed(tr, "queue_full", _now())
                fut.set_exception(DeadlineExceeded(
                    f"queue full ({self.config.max_queue})"))
                return fut
            self._queue.append((req, fut, t_sub, tr))
            tel.gauge("serving/queue_depth").set(len(self._queue))
            self._cv.notify_all()
        return fut

    # -- dispatch loop --------------------------------------------------------
    def _shed_expired_locked(self) -> None:
        """Drop queued requests whose deadline already passed — before
        any compute is spent on them (held lock)."""
        if not self._queue:
            return
        now = _now()
        kept: Deque = deque()
        for req, fut, t_sub, tr in self._queue:
            if req.deadline_s is not None and now - t_sub > req.deadline_s:
                self.telemetry.counter("serving/shed").inc()
                self.tracer.shed(tr, "deadline", now)
                fut.set_exception(DeadlineExceeded(
                    f"deadline {req.deadline_s}s passed while queued"))
            else:
                kept.append((req, fut, t_sub, tr))
        self._queue = kept
        self.telemetry.gauge("serving/queue_depth").set(len(self._queue))

    def _pick_group_locked(self) -> Optional[tuple]:
        """Least-recently-served group among those with work (active
        rows or queued requests), queue order breaking ties."""
        candidates: List[tuple] = list(self._active.keys())
        for req, _, _, _ in self._queue:
            gk = self.engine.group_key(req)
            if gk not in candidates:
                candidates.append(gk)
        if not candidates:
            return None
        return min(candidates,
                   key=lambda g: self._last_served.get(g, -1))

    def _admit_locked(self, gk: tuple, capacity: int,
                      now: float) -> List[RequestState]:
        """Pop up to `capacity` queued requests of group `gk` (FIFO) and
        prepare their device carries."""
        admitted: List[RequestState] = []
        kept: Deque = deque()
        for req, fut, t_sub, tr in self._queue:
            if len(admitted) < capacity \
                    and self.engine.group_key(req) == gk:
                try:
                    st = self.engine.prepare(req, fut, t_sub, now)
                    st.trace = tr
                    admitted.append(st)
                except Exception as e:  # bad request, not a loop error
                    self.tracer.shed(
                        tr, f"prepare_error:{type(e).__name__}", _now())
                    fut.set_exception(e)
            else:
                kept.append((req, fut, t_sub, tr))
        self._queue = kept
        self.telemetry.gauge("serving/queue_depth").set(len(self._queue))
        return admitted

    def _dispatch_loop(self) -> None:
        tel = self.telemetry
        cfg = self.config
        max_bucket = max(cfg.batch_buckets)
        while True:
            with self._cv:
                while not (self._queue or self._active or self._closed):
                    self._cv.wait()
                if self._closed and not self._draining:
                    break
                self._shed_expired_locked()
                gk = self._pick_group_locked()
                if gk is None:
                    if self._closed:
                        break
                    continue
                rows = self._active.pop(gk, [])
                now = _now()
                rows += self._admit_locked(gk, max_bucket - len(rows), now)
                if not rows:
                    continue
                self._round_no += 1
                self._last_served[gk] = self._round_no

            bucket = bucket_up(len(rows), cfg.batch_buckets)
            round_steps = cfg.round_steps or nfe_bucket(
                max(r.remaining for r in rows))
            tel.gauge("serving/batch_occupancy").set(len(rows) / bucket)
            tel.counter("serving/rows_real").inc(len(rows))
            tel.counter("serving/rows_padded").inc(bucket - len(rows))
            tel.counter("serving/rounds").inc()
            t_disp = _now()
            for r in rows:
                if r.first_dispatch_t is None:
                    r.first_dispatch_t = t_disp

            finished, _ = self.engine.advance(rows, bucket, round_steps)
            if self.tracer.enabled:
                # host timestamps + host-side dicts only: tracing must
                # not add a single device sync to the dispatch loop
                self.tracer.round(
                    rows, getattr(self.engine, "last_round_info", None),
                    t_disp, _now(), self._round_no)
            live = [r for r in rows if r.remaining > 0]
            if finished:
                t_fin = _now()
                out, _ = self.engine.finalize(
                    finished, bucket_up(len(finished), cfg.batch_buckets))
                if self.tracer.enabled:
                    self.tracer.finalize(
                        finished,
                        getattr(self.engine, "last_finalize_info", None),
                        t_fin, _now())
            with self._cv:
                if live:
                    self._active.setdefault(gk, []).extend(live)
                if finished:
                    self._completions.append((finished, out, _now()))
                    self._cv.notify_all()
                    # PR-5 bounded in-flight dispatch: never race more
                    # than max_inflight completed batches ahead of the
                    # completion thread's host sync
                    while len(self._completions) > cfg.max_inflight:
                        tel.counter("serving/backpressure_waits").inc()
                        self._cv.wait()
        # non-draining close: rows popped mid-round missed close()'s
        # cancel sweep — resolve their futures before exiting
        with self._cv:
            for rows in self._active.values():
                for r in rows:
                    r.future.set_exception(
                        SchedulerClosed("scheduler closed"))
            self._active.clear()
            for _, fut, _, _ in self._queue:
                fut.set_exception(SchedulerClosed("scheduler closed"))
            self._queue.clear()

    # -- completion loop ------------------------------------------------------
    def _completion_loop(self) -> None:
        tel = self.telemetry

        def hist(name: str):
            return tel.histogram(name, bounds=MS_BUCKET_BOUNDS)

        while True:
            with self._cv:
                while not self._completions and not self._dispatch_done:
                    self._cv.wait()
                if not self._completions and self._dispatch_done:
                    break
                rows, out, _t_disp = self._completions.popleft()
                self._cv.notify_all()     # free a backpressure slot
            _block_until_ready(out)
            host = _device_get(out)
            t_ready = _now()
            for i, r in enumerate(rows):
                latency_ms = (t_ready - r.submit_t) * 1e3
                queue_ms = ((r.first_dispatch_t or r.submit_t)
                            - r.submit_t) * 1e3
                device_ms = max(0.0, latency_ms - queue_ms - r.compile_ms)
                hist("serving/latency_ms").observe(latency_ms)
                hist("serving/queue_ms").observe(queue_ms)
                hist("serving/compile_ms").observe(r.compile_ms)
                hist("serving/device_ms").observe(device_ms)
                tel.counter("serving/requests_ok").inc()
                # the trace row carries the SAME decomposition the
                # histograms above observed — per-request span sums
                # reconcile with the aggregates by construction
                self.tracer.complete(r, queue_ms, r.compile_ms,
                                     device_ms, latency_ms, t_ready)
                r.future.set_result(SampleResult(
                    samples=host[i], request=r.req, queue_ms=queue_ms,
                    compile_ms=r.compile_ms, device_ms=device_ms,
                    latency_ms=latency_ms, rounds=r.rounds))

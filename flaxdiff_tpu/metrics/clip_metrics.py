"""CLIP-based image/text metrics (reference flaxdiff/metrics/images.py:14-111).

The CLIP model is cached at module level (the reference does the same);
loading requires downloadable weights, so construction is gated and the
similarity math is exposed as pure, weight-free functions.
"""
from __future__ import annotations


import jax
import jax.numpy as jnp
import numpy as np

from ..utils import denormalize_images
from .common import EvaluationMetric

_CLIP_CACHE: dict = {}


def register_clip_model(modelname: str, model, processor):
    """Register a (model, processor) pair under `modelname`, bypassing
    the pretrained download — offline tests inject a tiny random
    config-built FlaxCLIPModel here so the metric path (real model
    forward + similarity math) runs end to end without network."""
    _CLIP_CACHE[modelname] = (model, processor)


def cosine_similarity(a: jax.Array, b: jax.Array, eps: float = 1e-8
                      ) -> jax.Array:
    """Row-wise cosine similarity between [N, D] feature batches."""
    a = a / (jnp.linalg.norm(a, axis=-1, keepdims=True) + eps)
    b = b / (jnp.linalg.norm(b, axis=-1, keepdims=True) + eps)
    return jnp.sum(a * b, axis=-1)


def clip_score(image_feats: jax.Array, text_feats: jax.Array,
               w: float = 2.5) -> jax.Array:
    """CLIPScore (Hessel et al. 2021): w * max(cos, 0), averaged by caller."""
    return w * jnp.maximum(cosine_similarity(image_feats, text_feats), 0.0)


def _load_clip(modelname: str):
    if modelname in _CLIP_CACHE:
        return _CLIP_CACHE[modelname]
    try:
        from transformers import AutoProcessor, FlaxCLIPModel
        model = FlaxCLIPModel.from_pretrained(modelname, dtype=jnp.float16)
        processor = AutoProcessor.from_pretrained(modelname)
    except Exception as e:
        raise RuntimeError(
            f"Could not load CLIP weights for {modelname!r} (offline?). "
            "CLIP metrics need downloadable weights.") from e
    _CLIP_CACHE[modelname] = (model, processor)
    return model, processor


def _clip_features(images: np.ndarray, prompts, modelname: str):
    model, processor = _load_clip(modelname)
    inputs = processor(text=list(prompts), images=list(np.asarray(images)),
                       return_tensors="np", padding=True)
    img_feats = model.get_image_features(pixel_values=inputs["pixel_values"])
    txt_feats = model.get_text_features(input_ids=inputs["input_ids"],
                                        attention_mask=inputs["attention_mask"])
    return img_feats, txt_feats


def get_clip_metric(modelname: str = "openai/clip-vit-large-patch14",
                    prompt_key: str = "text") -> EvaluationMetric:
    """1 - cos(image, text): lower is better (reference images.py:54-83)."""

    def fn(samples, batch):
        imgs = np.asarray(denormalize_images(samples))
        img_f, txt_f = _clip_features(imgs, batch[prompt_key], modelname)
        return float(1.0 - jnp.mean(cosine_similarity(img_f, txt_f)))

    return EvaluationMetric(function=fn, name="clip_distance",
                            higher_is_better=False)


def get_clip_score_metric(modelname: str = "openai/clip-vit-large-patch14",
                          prompt_key: str = "text") -> EvaluationMetric:
    """Mean CLIPScore: higher is better (reference images.py:86-111)."""

    def fn(samples, batch):
        imgs = np.asarray(denormalize_images(samples))
        img_f, txt_f = _clip_features(imgs, batch[prompt_key], modelname)
        return float(jnp.mean(clip_score(img_f, txt_f)))

    return EvaluationMetric(function=fn, name="clip_score",
                            higher_is_better=True)

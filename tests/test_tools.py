"""Dev-tooling coverage: trace analyzer + bench stage CPU guards."""
import argparse
import gzip
import json
import time

import pytest


def _write_trace(path, events):
    with gzip.open(path, "wt") as f:
        json.dump({"traceEvents": events}, f)


DEVICE_EVENTS = [
    {"ph": "M", "name": "process_name", "pid": 3,
     "args": {"name": "/device:TPU:0"}},
    {"ph": "M", "name": "process_name", "pid": 9,
     "args": {"name": "/host:CPU"}},
    {"ph": "X", "pid": 3, "name": "attn1.2", "dur": 4000},
    {"ph": "X", "pid": 3, "name": "attn1.3", "dur": 2000},
    {"ph": "X", "pid": 3, "name": "fusion.7", "dur": 1000},
    {"ph": "X", "pid": 3, "name": "jit_train_step(123)", "dur": 99999},
    {"ph": "X", "pid": 9, "name": "host_only_thing", "dur": 5000},
]


def test_analyze_trace_aggregates_device_ops(tmp_path, capsys):
    from scripts.analyze_trace import main
    d = tmp_path / "plugins" / "profile" / "t1"
    d.mkdir(parents=True)
    _write_trace(d / "vm.trace.json.gz", DEVICE_EVENTS)
    assert main([str(tmp_path), "--top", "5"]) == 0
    out = capsys.readouterr().out
    assert "/device:TPU:0" in out
    assert "7.00 ms" in out   # total: 6 ms attn + 1 ms fusion
    # the attn FAMILY row aggregates attn1.2 + attn1.3 into 6.00 ms —
    # a falsifiable check that family() strips the SSA counter
    attn_rows = [ln for ln in out.splitlines()
                 if ln.startswith("attn")]
    assert len(attn_rows) == 1 and "6.00" in attn_rows[0], attn_rows
    assert "jit_train_step" not in out and "host_only_thing" not in out


def test_analyze_trace_skips_corrupt_and_host_only(tmp_path, capsys):
    """Newest capture truncated, next host-only, oldest good: the good
    one must be chosen (the wedged-tunnel scenario)."""
    from scripts.analyze_trace import main
    base = tmp_path / "plugins" / "profile"
    good = base / "2020_01_01"
    hostonly = base / "2021_01_01"
    corrupt = base / "2022_01_01"
    for d in (good, hostonly, corrupt):
        d.mkdir(parents=True)
    _write_trace(good / "vm.trace.json.gz", DEVICE_EVENTS)
    _write_trace(hostonly / "vm.trace.json.gz", [
        {"ph": "M", "name": "process_name", "pid": 9,
         "args": {"name": "/host:CPU"}},
        {"ph": "X", "pid": 9, "name": "x", "dur": 1}])
    with gzip.open(hostonly / "vm.trace.json.gz", "rb") as f:
        blob = f.read(40)
    (corrupt / "vm.trace.json.gz").write_bytes(blob)  # truncated gz
    assert main([str(tmp_path)]) == 0
    assert "2020_01_01" in capsys.readouterr().out


def test_analyze_trace_reports_host_only(tmp_path):
    from scripts.analyze_trace import main
    d = tmp_path / "p"
    d.mkdir()
    _write_trace(d / "vm.trace.json.gz", [
        {"ph": "M", "name": "process_name", "pid": 9,
         "args": {"name": "/host:CPU"}}])
    with pytest.raises(SystemExit, match="no device timeline"):
        main([str(d)])


def test_tpu_only_bench_stages_skip_on_cpu():
    """flashtune/attnpad/ablate must refuse to fake numbers off-TPU."""
    import bench
    args = argparse.Namespace(trace="bench_trace", quick=False)
    for stage in (bench.stage_flashtune, bench.stage_attnpad,
                  bench.stage_ablate, bench.stage_longseq):
        out = stage(args)
        assert out["platform"] == "cpu" and "skipped" in out


def test_chained_grad_ms_runs_on_cpu():
    """The shared timing harness itself is backend-agnostic."""
    import jax
    import jax.numpy as jnp

    import bench
    q = jax.random.normal(jax.random.PRNGKey(0), (1, 128, 2, 16),
                          jnp.float32)
    t0 = time.perf_counter()
    ms = bench.chained_grad_ms("xla", q, q, q, iters=2)
    assert 0 < ms < (time.perf_counter() - t0) * 1e3


def test_bench_budget_exhaustion_still_emits_final_line(tmp_path):
    """VERDICT r3 next #1: the orchestrator must produce a parseable
    final (non-partial) JSON line within its budget even when no stage
    fits — r3's run was killed still probing and parsed as null."""
    import os
    import subprocess
    import sys

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "bench.py"),
         "--quick", "--budget", "8",
         "--probe_timeout", "30", "--probe_budget", "30"],
        capture_output=True, text=True, timeout=240, env=env,
        cwd=tmp_path)
    lines = proc.stdout.strip().splitlines()
    final = json.loads(lines[-1])
    assert "partial" not in final
    assert all("skipped: budget" in v["status"]
               for v in final["stages"].values())


def test_bench_sigterm_emits_final_line(tmp_path):
    """The driver kills with SIGTERM at ITS wall clock (r3: rc 124,
    parsed null); the handler must flush the cumulative result first."""
    import os
    import signal
    import subprocess
    import sys

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "bench.py"), "--budget", "600"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        env=env, cwd=tmp_path)
    time.sleep(15)   # past the (cpu, ~2s) probe, inside the first stage
    proc.send_signal(signal.SIGTERM)
    out, _ = proc.communicate(timeout=60)
    final = json.loads(out.strip().splitlines()[-1])
    assert final.get("terminated", "").startswith("signal")
    assert "partial" not in final


# -- silent-exception gate (scripts/check_bare_except.py) ---------------------

def test_repo_has_no_new_silent_excepts():
    """Tier-1 gate: a new `except Exception: pass` outside the
    grandfathered allowlist fails the build — the observability layer's
    worst enemy is a failure that leaves no trace."""
    from scripts.check_bare_except import main
    assert main([]) == 0


def test_bare_except_gate_flags_new_offender(tmp_path, capsys):
    bad = tmp_path / "offender.py"
    bad.write_text(
        "def f():\n"
        "    try:\n"
        "        risky()\n"
        "    except Exception:\n"
        "        pass\n"
        "    try:\n"
        "        risky()\n"
        "    except (ValueError, BaseException):\n"
        "        ...\n")
    from scripts.check_bare_except import main
    assert main(["--root", str(bad)]) == 1
    err = capsys.readouterr().err
    assert "offender.py:4" in err and "offender.py:8" in err


def test_bare_except_gate_accepts_handlers_that_act(tmp_path):
    """Handlers that log, record, re-raise, or return a fallback are
    NOT silent — only do-nothing bodies fail."""
    ok = tmp_path / "fine.py"
    ok.write_text(
        "def f():\n"
        "    try:\n"
        "        risky()\n"
        "    except Exception as e:\n"
        "        record_event('x', 'y', detail=repr(e))\n"
        "    try:\n"
        "        risky()\n"
        "    except ValueError:\n"     # narrow catch: allowed even silent
        "        pass\n"
        "    try:\n"
        "        risky()\n"
        "    except Exception:\n"
        "        raise RuntimeError('context')\n")
    from scripts.check_bare_except import main
    assert main(["--root", str(ok)]) == 0


# -- metric-name gate (scripts/check_metric_names.py) -------------------------

def test_repo_metric_names_all_documented():
    """Tier-1 gate: every metric name emitted in flaxdiff_tpu/ appears
    in the docs/OBSERVABILITY.md reference table — an undocumented
    series is half-observability."""
    from scripts.check_metric_names import main
    assert main([]) == 0


def test_metric_gate_flags_undocumented_name(tmp_path, capsys):
    code = tmp_path / "emitter.py"
    code.write_text(
        "def f(reg):\n"
        "    reg.counter('secret/undocumented').inc()\n"
        "    reg.gauge('train/loss').set(1.0)\n")
    docs = tmp_path / "docs.md"
    docs.write_text("| `train/loss` | gauge | documented |\n")
    from scripts.check_metric_names import main
    assert main(["--root", str(code), "--docs", str(docs)]) == 1
    err = capsys.readouterr().err
    assert "secret/undocumented" in err and "train/loss" not in err


def test_metric_gate_wildcards_cover_fstrings_and_placeholders(tmp_path):
    """f-string emissions match docs entries with <placeholder>
    segments; exact names match either way; variable-name emissions
    are invisible (documented by hand)."""
    code = tmp_path / "emitter.py"
    code.write_text(
        "def f(reg, name):\n"
        "    reg.histogram(f'phase/{name}').observe(0.1)\n"
        "    reg.gauge('numerics/module/Conv_0/grad_norm').set(1.0)\n"
        "    reg.gauge(name).set(1.0)\n")       # variable: ungated
    docs = tmp_path / "docs.md"
    docs.write_text("- `phase/<name>` histograms\n"
                    "- `numerics/module/<module>/<stat>` rows\n")
    from scripts.check_metric_names import main
    assert main(["--root", str(code), "--docs", str(docs)]) == 0
    # remove the wildcard: the f-string prefix is now undocumented
    docs.write_text("- `numerics/module/<module>/<stat>` rows\n")
    assert main(["--root", str(code), "--docs", str(docs)]) == 1

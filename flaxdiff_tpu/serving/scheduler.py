"""Batched sampler scheduler: thread-safe admission, micro-batch
rounds with continuous admission, bounded in-flight dispatch, deadline
shedding, and per-request SLO telemetry.

Architecture (docs/SERVING.md):

- **submit()** enqueues a `SampleRequest` and returns a `ServingFuture`
  immediately. Overload is shed at the door (`max_queue`), deadlines
  are shed at dispatch time — both *before* any compute is spent,
  counted at `serving/shed`.
- A single **dispatch loop** drains the queue in rounds. Each round
  serves one compatibility group (least-recently-served for fairness),
  admits queued requests into the group's free capacity, pads the
  batch to a bucket, and advances every row by up to
  `round_steps` of its OWN trajectory through the engine's compiled
  program. Rows that complete exit mid-group ("continuous admission"):
  a 10-NFE request batched with a 50-NFE one returns after its own
  rounds, and its slot is refilled from the queue.
- Completed rows are handed (still device-resident, dispatch still
  async) to a **completion thread** that performs the only host syncs
  — `_block_until_ready` + `_device_get`, module-level seams so tests
  can count them, the PR-5 sync-free-loop convention. The dispatch
  loop keeps at most `max_inflight` completed batches in flight;
  beyond that it waits (genuine backpressure, counted at
  `serving/backpressure_waits`) instead of racing the device.
- **close(drain=True)** stops admission, finishes queued + active
  work, and joins both threads.

Failure semantics (docs/SERVING.md "Failure semantics",
serving/supervision.py): every round and completion fetch is a fault
barrier — a failing round poisons only its group, suspect requests are
convicted by binary-search solo re-runs (deterministic given seed),
innocent rows requeue with bounded attempts + backoff, device loss
drains and rebuilds the engine (prewarmed) under an
`EngineSupervisor`, and brownout degradation turns quality knobs
before anything is shed. No future is ever stranded: results,
`DeadlineExceeded`, `SchedulerClosed`, or a typed `ServingFault` —
even if a scheduler thread dies (chaos-tested).
"""
from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Tuple

from ..resilience import faults as _faults
from ..resilience.events import record_event
from ..resilience.retry import RetryPolicy
from ..telemetry.reqtrace import RequestTracer
from .engine import (DEFAULT_BATCH_BUCKETS, RequestState,
                     SamplerProgramEngine, bucket_up, nfe_bucket)
from .request import (DeadlineExceeded, SampleRequest, SampleResult,
                      SchedulerClosed, ServingFuture)
from .supervision import (BrownoutConfig, BrownoutPolicy, DeviceLost,
                          DRAINING, EngineSupervisor, SERVING,
                          ServingFault, classify)

# Millisecond-scale SLO latency buckets (the registry default bounds
# are seconds-scale training phases).
MS_BUCKET_BOUNDS: Tuple[float, ...] = (
    1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
    1000.0, 2500.0, 5000.0, 10000.0, 30000.0, 60000.0, 120000.0,
    300000.0)


# The scheduler's host-sync + clock primitives, module-level so tests
# can monkeypatch counting wrappers (the PR-5 seam convention): the
# dispatch loop itself must never block on device work.

def _block_until_ready(x) -> None:
    import jax
    jax.block_until_ready(x)


def _device_get(x):
    import jax
    import numpy as np
    return np.asarray(jax.device_get(x))


def _now() -> float:
    return time.perf_counter()


@dataclasses.dataclass
class SchedulerConfig:
    """Knobs for the dispatch loop.

    round_steps: trajectory steps advanced per round (the compiled
      program's scan length). 0 = run-to-completion: one round runs a
      group's whole (power-of-two-bucketed) max NFE — lowest overhead,
      but a short request then waits for the longest row in its round.
    batch_buckets: padded batch sizes; max(batch_buckets) caps rows
      per round.
    max_queue: admission cap; submits past it are shed at the door.
    max_inflight: completed batches allowed in flight to the
      completion thread before the dispatch loop backpressures.
    retry: bounded requeue budget + backoff schedule for
      failed-but-innocent requests (resilience/retry.py); a request's
      `attempts`-th failure requeues with `delays()[attempts-1]` of
      backoff until `max_attempts` is reached, then its future fails
      with `ServingFault(kind="retries_exhausted")`. Jitter is off by
      default so chaos replays are exactly deterministic.
    brownout: degradation thresholds (serving/supervision.py), or
      None to disable degrade-before-shed entirely.
    """
    round_steps: int = 8
    batch_buckets: Tuple[int, ...] = DEFAULT_BATCH_BUCKETS
    max_queue: int = 256
    max_inflight: int = 2
    drain_timeout_s: float = 120.0
    retry: RetryPolicy = dataclasses.field(
        default_factory=lambda: RetryPolicy(
            max_attempts=3, base_delay=0.05, max_delay=2.0, jitter=0.0))
    brownout: Optional[BrownoutConfig] = dataclasses.field(
        default_factory=BrownoutConfig)


@dataclasses.dataclass
class _Pending:
    """One queued request: the effective (possibly brownout-degraded)
    request, its future, submit timestamp, trace accumulator, failed
    attempts so far, original pre-degradation request, earliest
    re-dispatch time (retry backoff), and degradation flags."""
    req: SampleRequest
    fut: ServingFuture
    t_sub: float
    trace: Any = None
    attempts: int = 0
    orig_req: Optional[SampleRequest] = None
    not_before: float = 0.0
    degraded: Tuple[str, ...] = ()


class ServingScheduler:
    """Thread-safe request scheduler over a `SamplerProgramEngine`.

    Pass `autostart=False` to submit requests before the first round
    (tests use this to pin grouping deterministically), then `start()`.
    """

    def __init__(self, pipeline=None, engine=None,
                 config: Optional[SchedulerConfig] = None,
                 telemetry=None, autostart: bool = True,
                 engine_factory=None, profiler=None):
        if telemetry is None:
            from ..telemetry import global_telemetry
            telemetry = global_telemetry()
        if engine is None:
            if pipeline is None:
                raise ValueError("need a pipeline or an engine")
            engine = SamplerProgramEngine(pipeline, telemetry=telemetry)
            if engine_factory is None:
                # device loss tears the whole compiled-program cache
                # down with the engine — a fresh engine over the same
                # pipeline is the rebuild unit
                engine_factory = lambda: SamplerProgramEngine(  # noqa: E731
                    pipeline, telemetry=telemetry)
        self.engine = engine
        # None means device loss cannot rebuild: interrupted futures
        # fail with ServingFault(kind="device_lost") instead of hanging
        self.engine_factory = engine_factory
        self.config = config or SchedulerConfig()
        self.telemetry = telemetry
        # request-scoped tracing (telemetry/reqtrace.py): every call is
        # a no-op on a hub without a trace recorder, and a traced run
        # performs the IDENTICAL seam-counted host syncs as an untraced
        # one (counting-mock tested) — tracing is host bookkeeping only
        self.tracer = RequestTracer(telemetry)
        # device-profile hook (telemetry/devprof.py DeviceProfiler):
        # polled once per dispatch round with the round number — host
        # bookkeeping only (window open/close + capture parse), never
        # touches the program cache, so an armed profiler keeps warm
        # replays retrace-free (counting-mock + re_traces tested).
        # None (the default) costs one attribute check per round.
        self.profiler = profiler
        self.supervisor = EngineSupervisor(telemetry)
        self.brownout = (BrownoutPolicy(self.config.brownout, telemetry)
                         if self.config.brownout is not None else None)

        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._queue: Deque[_Pending] = deque()
        self._active: Dict[tuple, List[RequestState]] = {}
        self._completions: Deque[Tuple[List[RequestState], object, float]] \
            = deque()
        self._last_served: Dict[tuple, int] = {}
        self._round_no = 0
        self._closed = False
        self._draining = False
        self._dispatch_done = False
        self._processing = False     # completion thread mid-batch
        self._prewarm_args = None    # (protos, round_steps, buckets)

        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, name="serving-dispatch",
            daemon=True)
        self._completer = threading.Thread(
            target=self._completion_loop, name="serving-complete",
            daemon=True)
        self._started = False
        if autostart:
            self.start()

    # -- lifecycle ------------------------------------------------------------
    def prewarm(self, reqs: List[SampleRequest]) -> Dict[str, float]:
        """Startup hook: compile the compiled-program tuples the given
        traffic prototypes will hit — every (bucket, NFE, plan) under
        this scheduler's `round_steps`/`batch_buckets` config — BEFORE
        admission opens, so cold p50 never hits user traffic. Call
        before (or after) `start()`, but before submitting; delegates
        to `SamplerProgramEngine.prewarm`. The prototypes are recorded:
        an engine rebuild after device loss replays the same prewarm,
        so rebuilt traffic is also retrace-free."""
        self._prewarm_args = (list(reqs), self.config.round_steps,
                              self.config.batch_buckets)
        return self.engine.prewarm(reqs, self.config.round_steps,
                                   self.config.batch_buckets)

    def start(self) -> "ServingScheduler":
        if not self._started:
            self._started = True
            self._dispatcher.start()
            self._completer.start()
        return self

    def __enter__(self) -> "ServingScheduler":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close(drain=exc == (None, None, None))

    def close(self, drain: bool = True,
              timeout: Optional[float] = None) -> None:
        """Stop admission; with drain, finish queued + active work
        first. Idempotent."""
        timeout = self.config.drain_timeout_s if timeout is None else timeout
        with self._cv:
            self._closed = True
            self._draining = drain
            if not drain or not self._started:
                # nothing will ever drain an unstarted scheduler —
                # resolve pending futures instead of leaving waiters
                # hanging
                for e in self._queue:
                    e.fut.set_exception(SchedulerClosed("scheduler closed"))
                self._queue.clear()
                for rows in self._active.values():
                    for r in rows:
                        r.future.set_exception(
                            SchedulerClosed("scheduler closed"))
                self._active.clear()
            self._cv.notify_all()
        if self._started:
            self._dispatcher.join(timeout)
        with self._cv:
            self._dispatch_done = True
            self._cv.notify_all()
        if self._started:
            self._completer.join(timeout)

    # -- pool introspection (serving/replica.py) ------------------------------
    # Host-side accessors for the replica/front-door layer: routing
    # reads these on every submit, so they must stay lock-bounded
    # bookkeeping — no device work, no blocking waits.
    @property
    def closed(self) -> bool:
        """True once close() (or a thread-death sweep) stopped
        admission — the replica layer's DEAD signal."""
        return self._closed

    def queue_depth(self) -> int:
        """Queued (not yet dispatched) requests right now."""
        with self._lock:
            return len(self._queue)

    def load(self) -> int:
        """Total requests this scheduler is responsible for: queued +
        active rows + completed batches awaiting the host fetch. The
        front door's least-loaded routing key."""
        with self._lock:
            n = len(self._queue)
            for rows in self._active.values():
                n += len(rows)
            for rows, _, _ in self._completions:
                n += len(rows)
            return n

    def cancel(self, fut: ServingFuture) -> bool:
        """Best-effort cancel of a QUEUED request by its future — the
        front door reaps a hedge loser with this before it costs any
        compute. A request already dispatched (active or in flight to
        the completion thread) is not cancellable; first-set-wins on
        the future makes its late result harmless. Returns True when a
        queued entry was removed."""
        with self._cv:
            hit = False
            kept: Deque = deque()
            for e in self._queue:
                if e.fut is fut and not hit:
                    hit = True
                    self.telemetry.counter("serving/cancelled").inc()
                    self.tracer.shed(e.trace, "cancelled", _now())
                    e.fut.set_exception(
                        SchedulerClosed("cancelled by caller"))
                else:
                    kept.append(e)
            if hit:
                self._queue = kept
                self.telemetry.gauge("serving/queue_depth").set(
                    len(self._queue))
            return hit

    # -- admission ------------------------------------------------------------
    def submit(self, req: SampleRequest,
               trace_ctx=None) -> ServingFuture:
        """Enqueue one request. Never blocks: overload and post-close
        submits come back as exceptions on the returned future.
        Brownout degradation applies here, at the admission door: under
        queue pressure or recent faults the request is downgraded (NFE
        cap, forced cache plan) instead of shed — the effective request
        determines grouping, and the result carries the flags.
        `trace_ctx` (a `RequestTracer.context` dict) joins this hop's
        spans to an upstream trace — the front door passes its minted
        id so one trace spans door -> replica -> serving rounds."""
        fut = ServingFuture()
        tel = self.telemetry
        with self._cv:
            if self._closed:
                fut.set_exception(SchedulerClosed("scheduler closed"))
                return fut
            tel.counter("serving/requests_in").inc()
            t_sub = _now()
            tr = self.tracer.begin(req, t_sub,   # None on disabled hub
                                   parent=trace_ctx)
            if len(self._queue) >= self.config.max_queue:
                tel.counter("serving/shed").inc()
                self.tracer.shed(tr, "queue_full", _now())
                fut.set_exception(DeadlineExceeded(
                    f"queue full ({self.config.max_queue})"))
                return fut
            req_eff, flags = req, ()
            if self.brownout is not None:
                tier = self.brownout.tier(len(self._queue),
                                          self.config.max_queue, t_sub)
                req_eff, flags = self.brownout.apply(req, tier)
                if flags:
                    self.tracer.note(tr, "brownout", t_sub, tier=tier,
                                     flags=list(flags))
            self._queue.append(_Pending(req_eff, fut, t_sub, tr,
                                        orig_req=req, degraded=flags))
            tel.gauge("serving/queue_depth").set(len(self._queue))
            self._cv.notify_all()
        return fut

    # -- dispatch loop --------------------------------------------------------
    def _shed_expired_locked(self) -> None:
        """Drop queued requests whose deadline already passed — before
        any compute is spent on them (held lock)."""
        if not self._queue:
            return
        now = _now()
        kept: Deque = deque()
        for e in self._queue:
            if e.req.deadline_s is not None \
                    and now - e.t_sub > e.req.deadline_s:
                self.telemetry.counter("serving/shed").inc()
                self.tracer.shed(e.trace, "deadline", now)
                e.fut.set_exception(DeadlineExceeded(
                    f"deadline {e.req.deadline_s}s passed while queued"))
            else:
                kept.append(e)
        self._queue = kept
        self.telemetry.gauge("serving/queue_depth").set(len(self._queue))

    def _shed_expired_active(self, rows: List[RequestState],
                             now: float) -> List[RequestState]:
        """Mid-flight deadline check at the round boundary: a request
        whose deadline passed BETWEEN rounds is shed before the next
        round spends more compute on it (its sunk rounds are lost, but
        nobody is waiting for the result anymore). Counted at
        `serving/shed` + `serving/shed_midflight`; the trace row closes
        with `outcome=shed:deadline`."""
        kept: List[RequestState] = []
        for r in rows:
            if r.req.deadline_s is not None \
                    and now - r.submit_t > r.req.deadline_s:
                self.telemetry.counter("serving/shed").inc()
                self.telemetry.counter("serving/shed_midflight").inc()
                self.tracer.shed(r.trace, "deadline", now)
                r.future.set_exception(DeadlineExceeded(
                    f"deadline {r.req.deadline_s}s passed mid-flight "
                    f"after {r.rounds} round(s)"))
            else:
                kept.append(r)
        return kept

    def _pick_group_locked(self) -> Optional[tuple]:
        """Least-recently-served group among those with work (active
        rows or queued requests), queue order breaking ties."""
        candidates: List[tuple] = list(self._active.keys())
        for e in self._queue:
            gk = self.engine.group_key(e.req)
            if gk not in candidates:
                candidates.append(gk)
        if not candidates:
            return None
        return min(candidates,
                   key=lambda g: self._last_served.get(g, -1))

    def _admit_locked(self, gk: tuple, capacity: int,
                      now: float) -> List[RequestState]:
        """Pop up to `capacity` queued requests of group `gk` (FIFO) and
        prepare their device carries. Requeued entries still inside
        their retry backoff window (`not_before`) are skipped."""
        admitted: List[RequestState] = []
        kept: Deque = deque()
        for e in self._queue:
            if len(admitted) < capacity and e.not_before <= now \
                    and self.engine.group_key(e.req) == gk:
                try:
                    st = self.engine.prepare(e.req, e.fut, e.t_sub, now)
                    st.trace = e.trace
                    st.attempts = e.attempts
                    st.orig_req = e.orig_req or e.req
                    st.degraded = e.degraded
                    admitted.append(st)
                except Exception as exc:  # bad request, not a loop error
                    self.tracer.shed(
                        e.trace, f"prepare_error:{type(exc).__name__}",
                        _now())
                    e.fut.set_exception(exc)
            else:
                kept.append(e)
        self._queue = kept
        self.telemetry.gauge("serving/queue_depth").set(len(self._queue))
        return admitted

    # -- fault isolation ------------------------------------------------------
    def _checked_advance(self, rows: List[RequestState], bucket: int,
                         round_steps: int):
        """One engine round behind the serving fault barriers
        (resilience/faults.py): `serving.device_lost` (flag -> raises
        `DeviceLost`) models a dead chip, `serving.round` is polled
        once per row with `key="seed:<seed>:"` so a per-key plan can
        deterministically poison ONE request no matter what it is
        batched with. One dict lookup each with no plan armed."""
        if _faults.check("serving.device_lost"):
            raise DeviceLost("injected fault at serving.device_lost")
        for r in rows:
            _faults.check("serving.round", key=f"seed:{r.req.seed}:")
        return self.engine.advance(rows, bucket, round_steps)

    def _fail_state(self, r: RequestState, fault: ServingFault,
                    outcome: str) -> None:
        """Resolve one in-flight request's future with a typed fault
        and close its trace row with the fault outcome."""
        self.tracer.fail(r, outcome, _now())
        r.future.set_exception(fault)

    def _requeue_locked(self, states: List[RequestState], now: float,
                        cause: Optional[BaseException] = None,
                        penalize: bool = True) -> None:
        """Re-enter failed-but-innocent requests into the queue for a
        bit-exact replay from scratch (`SampleRequest` carries seed,
        NFE, and cache plan — `prepare` reconstructs the whole carry).
        With `penalize`, the attempt counts against the bounded retry
        budget and the re-dispatch waits out the policy's backoff;
        rebuild interruptions requeue unpenalized (the device fault was
        not theirs). Held lock.

        Close race: a non-draining `close()` sweeps the queue and
        resolves everything it can see, but rows a rebuild (or a
        fetch-fault retry) holds in a local list at that instant are
        invisible to the sweep — requeueing them afterwards would
        strand their futures with the dispatch loop already exiting.
        Resolve them here instead (chaos-tested)."""
        if self._closed and not self._draining:
            for r in states:
                self.tracer.shed(r.trace, "closed", now)
                r.future.set_exception(
                    SchedulerClosed("scheduler closed"))
            return
        retry = self.config.retry
        delays = retry.delays()
        for r in states:
            attempts = r.attempts + (1 if penalize else 0)
            if penalize and attempts >= retry.max_attempts:
                self.telemetry.counter("serving/retries_exhausted").inc()
                self._fail_state(r, ServingFault(
                    f"gave up after {attempts} attempt(s): {cause!r}",
                    kind="retries_exhausted", request=r.orig_req,
                    attempts=attempts, cause=cause),
                    "fault:retries_exhausted")
                continue
            delay = 0.0
            if penalize and delays:
                delay = delays[min(attempts - 1, len(delays) - 1)]
            self.telemetry.counter("serving/requeued").inc()
            self.tracer.note(r.trace, "requeued", now,
                             attempts=attempts,
                             backoff_s=round(delay, 3))
            self._queue.append(_Pending(
                r.orig_req or r.req, r.future, r.submit_t, r.trace,
                attempts=attempts, orig_req=r.orig_req,
                not_before=now + delay, degraded=r.degraded))
        self.telemetry.gauge("serving/queue_depth").set(len(self._queue))

    def _convict(self, rows: List[RequestState], buckets: Tuple[int, ...],
                 round_steps: int):
        """Binary-search eviction after a batch fault: requests are
        deterministic given their seed, so any suspect row can be
        re-run solo from scratch to convict. Probes re-prepare fresh
        carries (the failed round may have poisoned the old ones) and
        run ONE round through the same fault barriers; a subset that
        passes is innocent wholesale, a failing singleton is convicted.
        A transient fault that does not reproduce convicts nobody.
        Returns (guilty, innocent). `DeviceLost` during a probe
        propagates — the caller re-routes to the rebuild path."""

        def probe(subset) -> Optional[BaseException]:
            self.telemetry.counter("serving/probe_rounds").inc()
            try:
                sts = [self.engine.prepare(r.req, ServingFuture(),
                                           r.submit_t, _now())
                       for r in subset]
                self._checked_advance(
                    sts, bucket_up(len(sts), buckets), round_steps)
                return None
            except (KeyboardInterrupt, SystemExit, DeviceLost):
                raise
            except BaseException as e:  # noqa: BLE001 — verdict, not flow
                return e

        def search(subset):
            if probe(subset) is None:
                return [], list(subset)
            if len(subset) == 1:
                return list(subset), []
            mid = len(subset) // 2
            g1, i1 = search(subset[:mid])
            g2, i2 = search(subset[mid:])
            if not g1 and not g2:
                # halves pass solo but the whole failed together:
                # transient — nobody convicted, everyone requeues
                return [], list(subset)
            return g1 + g2, i1 + i2

        if len(rows) == 1:
            return search(list(rows))
        # the full batch ALREADY failed — go straight to the halves; a
        # one-shot transient then passes both and convicts nobody
        mid = len(rows) // 2
        g1, i1 = search(list(rows[:mid]))
        g2, i2 = search(list(rows[mid:]))
        if not g1 and not g2:
            return [], list(rows)
        return g1 + g2, i1 + i2

    def _on_round_failure(self, gk: tuple, rows: List[RequestState],
                          exc: BaseException, buckets: Tuple[int, ...],
                          round_steps: int) -> None:
        """Fault-isolate one failed round: classify, convict or
        rebuild, requeue the innocent. The failing round poisons only
        its own group — other groups' active rows are untouched (except
        under device loss, where every carry references a dead
        device)."""
        kind = classify(exc)
        now = _now()
        self.telemetry.counter("serving/round_faults").inc()
        record_event("serving_fault", "serving.round",
                     detail=f"{kind}: {exc!r} rows={len(rows)}")
        if self.brownout is not None:
            self.brownout.note_fault(now)
        for r in rows:
            self.tracer.note(r.trace, "round_fault", now,
                             fault_kind=kind,
                             error=type(exc).__name__)
        if kind == "device_lost":
            self._supervised_rebuild(exc, rows)
            return
        try:
            guilty, innocent = self._convict(rows, buckets, round_steps)
        except DeviceLost as e2:
            self._supervised_rebuild(e2, rows)
            return
        for r in guilty:
            self.telemetry.counter("serving/quarantined").inc()
            self.tracer.note(r.trace, "quarantined", _now())
            self._fail_state(r, ServingFault(
                f"request convicted by solo re-run after a batch "
                f"fault: {exc!r}", kind="poisoned", request=r.orig_req,
                attempts=r.attempts + 1, cause=exc), "fault:poisoned")
        with self._cv:
            self._requeue_locked(innocent, now, cause=exc)
            self._cv.notify_all()

    def _supervised_rebuild(self, exc: BaseException,
                            rows: List[RequestState]) -> None:
        """Device-level failure: drain in-flight completions, tear down
        the program cache with the dead engine, rebuild on the
        surviving device set, re-run prewarm, and requeue every
        interrupted request (unpenalized — the fault was not theirs).
        Without an `engine_factory` the interrupted futures fail typed
        instead of hanging."""
        tel = self.telemetry
        tel.counter("serving/device_lost").inc()
        record_event("serving_fault", "serving.device_lost",
                     detail=repr(exc))
        if self.brownout is not None:
            self.brownout.note_fault(_now())
        t0 = _now()
        with self._cv:
            interrupted = list(rows)
            for rs in self._active.values():
                interrupted.extend(rs)
            self._active.clear()
            # DRAINING: let the completion thread settle (or fail and
            # requeue) every batch already handed to it before the old
            # engine is torn down
            self.supervisor.set_state(DRAINING)
            while self._completions or self._processing:
                self._cv.wait(0.05)
        for r in interrupted:
            self.tracer.note(r.trace, "rebuild_interrupt", _now())
        if self.engine_factory is None:
            for r in interrupted:
                self._fail_state(r, ServingFault(
                    f"device lost and no engine_factory to rebuild: "
                    f"{exc!r}", kind="device_lost", request=r.orig_req,
                    attempts=r.attempts, cause=exc),
                    "fault:device_lost")
            self.supervisor.set_state(SERVING)
            return
        self.engine = self.supervisor.rebuild(
            self.engine_factory, exc, prewarm_args=self._prewarm_args)
        self.tracer.rebuild(t0, _now(), {
            "reason": type(exc).__name__,
            "interrupted": len(interrupted),
            "prewarmed": bool(self._prewarm_args)})
        with self._cv:
            self._requeue_locked(interrupted, _now(), cause=exc,
                                 penalize=False)
            self._cv.notify_all()

    def _fail_all_pending(self, fault: ServingFault) -> None:
        """Last-resort sweep when a scheduler thread dies: every
        queued and in-flight future resolves (first set wins, so a
        result the completion thread is delivering concurrently is
        never clobbered)."""
        with self._cv:
            self._closed = True
            # a completion thread dying mid-batch must not leave the
            # rebuild DRAINING wait spinning on `_processing`
            self._processing = False
            for e in self._queue:
                e.fut.set_exception(fault)
            self._queue.clear()
            for rows in self._active.values():
                for r in rows:
                    self._fail_state(r, fault, f"fault:{fault.kind}")
            self._active.clear()
            for rows, _, _ in self._completions:
                for r in rows:
                    self._fail_state(r, fault, f"fault:{fault.kind}")
            self._completions.clear()
            self._cv.notify_all()

    def _dispatch_loop(self) -> None:
        """Crash guard around the real loop: a dying dispatch thread
        must fail every pending future typed, never strand them
        (regression-tested)."""
        try:
            self._dispatch_rounds()
        except (KeyboardInterrupt, SystemExit):
            raise
        except BaseException as e:  # noqa: BLE001 — last-resort guard
            record_event("serving_fault", "serving.dispatch",
                         detail=f"dispatch thread died: {e!r}")
            self._fail_all_pending(ServingFault(
                f"dispatch thread died: {e!r}", kind="scheduler_died",
                cause=e))

    def _dispatch_rounds(self) -> None:
        tel = self.telemetry
        cfg = self.config
        while True:
            with self._cv:
                while not (self._queue or self._active or self._closed):
                    self._cv.wait()
                if self._closed and not self._draining:
                    break
                self._shed_expired_locked()
                gk = self._pick_group_locked()
                if gk is None:
                    if self._closed and not self._completions \
                            and not self._processing:
                        # a draining close may still see a fetch-fault
                        # requeue from the completion thread — only
                        # exit once nothing in flight can re-enter
                        break
                    self._cv.wait(0.02)
                    continue
                now = _now()
                # brownout tier 3: shrink rounds to the smallest bucket
                # (smaller blast radius + memory footprint) before any
                # shedding happens
                tier = (self.brownout.tier(len(self._queue),
                                           cfg.max_queue, now)
                        if self.brownout is not None else 0)
                buckets = cfg.batch_buckets
                if tier >= 3:
                    buckets = (min(cfg.batch_buckets),)
                max_bucket = max(buckets)
                rows = self._shed_expired_active(
                    self._active.pop(gk, []), now)
                if len(rows) > max_bucket:
                    # bucket shrink mid-group: overflow rows stay
                    # active and ride the group's next round
                    self._active[gk] = rows[max_bucket:]
                    rows = rows[:max_bucket]
                rows += self._admit_locked(gk, max_bucket - len(rows),
                                           now)
                if not rows:
                    # group had only backoff-parked entries (or every
                    # row was shed): wait for the earliest retry
                    self._cv.wait(0.02)
                    continue
                if tier >= 3:
                    tel.counter("serving/brownout_bucket_shrunk").inc()
                self._round_no += 1
                self._last_served[gk] = self._round_no

            if self.profiler is not None:
                # outside the lock: the poll may parse a closing
                # window's capture (host-only work that must not stall
                # admission)
                self.profiler.poll_round(self._round_no)
            bucket = bucket_up(len(rows), buckets)
            round_steps = cfg.round_steps or nfe_bucket(
                max(r.remaining for r in rows))
            tel.gauge("serving/batch_occupancy").set(len(rows) / bucket)
            tel.counter("serving/rows_real").inc(len(rows))
            tel.counter("serving/rows_padded").inc(bucket - len(rows))
            tel.counter("serving/rounds").inc()
            t_disp = _now()
            for r in rows:
                if r.first_dispatch_t is None:
                    r.first_dispatch_t = t_disp

            try:
                finished, _ = self._checked_advance(rows, bucket,
                                                    round_steps)
                if self.tracer.enabled:
                    # host timestamps + host-side dicts only: tracing
                    # must not add a single device sync to the
                    # dispatch loop
                    self.tracer.round(
                        rows,
                        getattr(self.engine, "last_round_info", None),
                        t_disp, _now(), self._round_no)
                live = [r for r in rows if r.remaining > 0]
                if finished:
                    t_fin = _now()
                    out, _ = self.engine.finalize(
                        finished, bucket_up(len(finished), buckets))
                    if self.tracer.enabled:
                        self.tracer.finalize(
                            finished,
                            getattr(self.engine,
                                    "last_finalize_info", None),
                            t_fin, _now())
            except (KeyboardInterrupt, SystemExit):
                raise
            except BaseException as e:  # noqa: BLE001 — fault barrier
                # the failing round poisons only its group: convict /
                # requeue / rebuild, then keep serving everyone else
                self._on_round_failure(gk, rows, e, buckets,
                                       round_steps)
                continue
            with self._cv:
                if live:
                    self._active.setdefault(gk, []).extend(live)
                if finished:
                    self._completions.append((finished, out, _now()))
                    self._cv.notify_all()
                    # PR-5 bounded in-flight dispatch: never race more
                    # than max_inflight completed batches ahead of the
                    # completion thread's host sync
                    while len(self._completions) > cfg.max_inflight:
                        tel.counter("serving/backpressure_waits").inc()
                        self._cv.wait()
        # non-draining close: rows popped mid-round missed close()'s
        # cancel sweep — resolve their futures before exiting
        with self._cv:
            for rows in self._active.values():
                for r in rows:
                    r.future.set_exception(
                        SchedulerClosed("scheduler closed"))
            self._active.clear()
            for e in self._queue:
                e.fut.set_exception(SchedulerClosed("scheduler closed"))
            self._queue.clear()

    # -- completion loop ------------------------------------------------------
    def _completion_loop(self) -> None:
        """Crash guard around the real loop (mirrors the dispatch
        guard): a dying completion thread fails every pending future
        typed and unblocks the dispatch loop's backpressure wait."""
        try:
            self._completion_rounds()
        except (KeyboardInterrupt, SystemExit):
            raise
        except BaseException as e:  # noqa: BLE001 — last-resort guard
            record_event("serving_fault", "serving.complete",
                         detail=f"completion thread died: {e!r}")
            self._fail_all_pending(ServingFault(
                f"completion thread died: {e!r}", kind="scheduler_died",
                cause=e))

    def _completion_rounds(self) -> None:
        tel = self.telemetry

        def hist(name: str):
            return tel.histogram(name, bounds=MS_BUCKET_BOUNDS)

        while True:
            with self._cv:
                while not self._completions and not self._dispatch_done:
                    self._cv.wait()
                if not self._completions and self._dispatch_done:
                    break
                rows, out, _t_disp = self._completions.popleft()
                self._processing = True
                self._cv.notify_all()     # free a backpressure slot
            try:
                # serving.fetch fault barrier: a failed readback is a
                # fault of the FETCH, not of any request — the batch
                # requeues for a bit-exact replay from scratch
                _faults.check("serving.fetch")
                _block_until_ready(out)
                host = _device_get(out)
            except (KeyboardInterrupt, SystemExit):
                raise
            except BaseException as e:  # noqa: BLE001 — fault barrier
                tel.counter("serving/fetch_faults").inc()
                record_event("serving_fault", "serving.fetch",
                             detail=repr(e))
                now = _now()
                if self.brownout is not None:
                    self.brownout.note_fault(now)
                for r in rows:
                    self.tracer.note(r.trace, "fetch_fault", now,
                                     error=type(e).__name__)
                with self._cv:
                    if self._dispatch_done:
                        # nothing left to serve a requeue — fail typed
                        for r in rows:
                            self._fail_state(r, ServingFault(
                                f"completion fetch failed after "
                                f"dispatch ended: {e!r}",
                                kind="fetch_error", request=r.orig_req,
                                attempts=r.attempts, cause=e),
                                "fault:fetch_error")
                    else:
                        self._requeue_locked(rows, now, cause=e)
                    self._processing = False
                    self._cv.notify_all()
                continue
            t_ready = _now()
            for i, r in enumerate(rows):
                latency_ms = (t_ready - r.submit_t) * 1e3
                queue_ms = ((r.first_dispatch_t or r.submit_t)
                            - r.submit_t) * 1e3
                device_ms = max(0.0, latency_ms - queue_ms - r.compile_ms)
                hist("serving/latency_ms").observe(latency_ms)
                hist("serving/queue_ms").observe(queue_ms)
                hist("serving/compile_ms").observe(r.compile_ms)
                hist("serving/device_ms").observe(device_ms)
                tel.counter("serving/requests_ok").inc()
                # the trace row carries the SAME decomposition the
                # histograms above observed — per-request span sums
                # reconcile with the aggregates by construction
                self.tracer.complete(r, queue_ms, r.compile_ms,
                                     device_ms, latency_ms, t_ready)
                r.future.set_result(SampleResult(
                    samples=host[i], request=r.req, queue_ms=queue_ms,
                    compile_ms=r.compile_ms, device_ms=device_ms,
                    latency_ms=latency_ms, rounds=r.rounds,
                    attempts=r.attempts, degraded=r.degraded))
            with self._cv:
                self._processing = False
                self._cv.notify_all()

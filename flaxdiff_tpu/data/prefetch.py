"""Pipelined host-side transforms: overlap per-batch CPU work (text
encoding, augmentation) with device steps.

SURVEY §7.3(4): the reference runs its CLIP text tower INSIDE the jitted
train step (reference general_diffusion_trainer.py:275,292), spending MXU
cycles on a frozen encoder every step; round-1 of this framework encoded
on the host synchronously, serializing input against the device. This
module is the third option: encode on the host in a background thread,
`depth` batches ahead, so encoding cost hides behind device compute
entirely when encode_time <= step_time (measured: a CLIP-L text tower on
77 tokens is ~5-15 ms on host vs ~100+ ms UNet steps, so prefetch wins
over in-jit — which also pays HBM for the frozen tower's weights — and
over blocking host encode; see bench note in scripts/bench_text_encode.py).
"""
from __future__ import annotations

import queue
import threading
from typing import Callable, Iterator, TypeVar

T = TypeVar("T")
U = TypeVar("U")

_SENTINEL = object()


def prefetch_map(fn: Callable[[T], U], it: Iterator[T],
                 depth: int = 2) -> Iterator[U]:
    """Apply `fn` to items of `it` in a daemon thread, keeping up to
    `depth` results ready. Order-preserving. Exceptions in `fn` or the
    source iterator re-raise at the consumer's next() (the data-layer
    fault-surfacing behavior of reference online_loader.py:980-988).

    Closing/abandoning the returned generator stops the worker: its
    queue puts poll a stop flag, so a consumer that walks away (common
    in tests and chunked training loops) doesn't leave a thread blocked
    on a full queue for the life of the process."""
    if depth < 1:
        raise ValueError("depth must be >= 1")
    q: "queue.Queue" = queue.Queue(maxsize=depth)
    stop = threading.Event()

    def put(item) -> bool:
        """Blocking put that gives up when the consumer is gone."""
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def worker():
        try:
            for item in it:
                if not put(fn(item)):
                    return
        except BaseException as e:  # surfaced on the consumer side
            # structured visibility BEFORE the re-raise lands: a consumer
            # that swallows the exception (or dies with it) still leaves
            # the pipeline failure in the resilience event stream
            from ..resilience.events import record_event
            record_event("pipeline_error", "data.prefetch",
                         detail=f"{type(e).__name__}: {e}")
            put((_SENTINEL, e))
            return
        put((_SENTINEL, None))

    t = threading.Thread(target=worker, daemon=True,
                         name="flaxdiff-prefetch")
    t.start()

    try:
        while True:
            got = q.get()
            if isinstance(got, tuple) and len(got) == 2 \
                    and got[0] is _SENTINEL:
                if got[1] is not None:
                    raise got[1]
                return
            yield got
    finally:
        stop.set()

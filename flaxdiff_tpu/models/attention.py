"""Attention modules built on the ops-layer dispatcher.

Capability parity with reference flaxdiff/models/attention.py:34-380
(EfficientAttention/NormalAttention -> one AttentionLayer with a backend
switch; FlaxGEGLU/FlaxFeedForward -> GEGLUFeedForward; BasicTransformerBlock;
TransformerBlock with optional projection). The flash path is the
first-party Pallas kernel in ops/flash_attention.py.
"""
from __future__ import annotations

import os
from typing import Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..ops.attention import dot_product_attention, dot_product_attention_bhld
from ..typing import Dtype
from .common import kernel_init


class _ProjToHeads(nn.Module):
    """[B, L, C] -> [B, H, L, D] projection whose params are
    shape/name-identical to `nn.DenseGeneral((H, D))` (kernel (C,H,D),
    bias (H,D)) — checkpoints swap freely between layouts. The output
    permutation is folded into the projection dot_general itself, so no
    separate transpose op ever exists for XLA to materialize."""

    heads: int
    dim_head: int
    use_bias: bool = True
    dtype: Optional[Dtype] = None
    precision: Optional[jax.lax.Precision] = None
    kernel_init: Callable = kernel_init(1.0)

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        c = x.shape[-1]
        # init on the FLATTENED (C, H*D) shape exactly as
        # nn.DenseGeneral((H, D)) does (its kernel_init_wrap): a
        # variance-scaling init drawn directly on (C, H, D) would see
        # fan_in=H*C / fan_out=D*C and start ~sqrt(H)x narrower than
        # the layout-independent checkpoint contract promises
        kernel = self.param(
            "kernel",
            lambda key, shape, dtype=jnp.float32: self.kernel_init(
                key, (c, self.heads * self.dim_head), dtype
            ).reshape(shape),
            (c, self.heads, self.dim_head))
        bias = (self.param("bias", nn.initializers.zeros,
                           (self.heads, self.dim_head))
                if self.use_bias else None)
        x, kernel, bias = nn.dtypes.promote_dtype(
            x, kernel, bias, dtype=self.dtype)
        y = jnp.einsum("blc,chd->bhld", x, kernel,
                       precision=self.precision)
        if bias is not None:
            y = y + bias[None, :, None, :]
        return y


class _ProjFromHeads(nn.Module):
    """[B, H, L, D] -> [B, L, C]; params identical to
    `nn.DenseGeneral(C, axis=(-2, -1))` on a [B, L, H, D] input
    (kernel (H,D,C), bias (C,))."""

    features: int
    heads: int
    dim_head: int
    use_bias: bool = True
    dtype: Optional[Dtype] = None
    precision: Optional[jax.lax.Precision] = None
    kernel_init: Callable = kernel_init(1.0)

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        # flattened-shape init matching nn.DenseGeneral(C, axis=(-2,-1))
        # (see _ProjToHeads)
        kernel = self.param(
            "kernel",
            lambda key, shape, dtype=jnp.float32: self.kernel_init(
                key, (self.heads * self.dim_head, self.features), dtype
            ).reshape(shape),
            (self.heads, self.dim_head, self.features))
        bias = (self.param("bias", nn.initializers.zeros,
                           (self.features,))
                if self.use_bias else None)
        x, kernel, bias = nn.dtypes.promote_dtype(
            x, kernel, bias, dtype=self.dtype)
        y = jnp.einsum("bhld,hdc->blc", x, kernel,
                       precision=self.precision)
        if bias is not None:
            y = y + bias
        return y


def head_projection(bhld: bool, *, heads: int, dim_head: int,
                    use_bias: bool, dtype, precision, kernel_init,
                    name: str) -> nn.Module:
    """The q/k/v projection for a layout: [B,L,C]->[B,L,H,D]
    (DenseGeneral) or ->[B,H,L,D] (_ProjToHeads). One constructor shared
    by every attention module so the two layouts cannot drift (same
    param names/shapes AND the caller's exact init in both)."""
    if bhld:
        return _ProjToHeads(heads=heads, dim_head=dim_head,
                            use_bias=use_bias, dtype=dtype,
                            precision=precision, kernel_init=kernel_init,
                            name=name)
    return nn.DenseGeneral((heads, dim_head), use_bias=use_bias,
                           dtype=dtype, precision=precision,
                           kernel_init=kernel_init, name=name)


def head_out_projection(bhld: bool, *, features: int, heads: int,
                        dim_head: int, use_bias: bool, dtype, precision,
                        kernel_init, name: str = "to_out") -> nn.Module:
    """The output projection back to [B,L,C] for either layout."""
    if bhld:
        return _ProjFromHeads(features=features, heads=heads,
                              dim_head=dim_head, use_bias=use_bias,
                              dtype=dtype, precision=precision,
                              kernel_init=kernel_init, name=name)
    return nn.DenseGeneral(features, axis=(-2, -1), use_bias=use_bias,
                           dtype=dtype, precision=precision,
                           kernel_init=kernel_init, name=name)


class AttentionLayer(nn.Module):
    """Multi-head self/cross attention over [B, L, C] (+[B,H,W,C] auto-flatten).

    backend: "auto" | "flash" | "xla".
    bhld: project q/k/v straight into the flash kernel's native
    [B, H, L, D] layout — the head permutation is folded into the
    projection matmuls, so the per-operand transposes (and XLA's
    materialized copies around the pallas custom call — ~750 copy
    ops/step in the r3 trace) disappear. None (default) reads
    FLAXDIFF_ATTN_BHLD at trace time so the bench can A/B without a
    model rebuild — in MULTI-HOST runs that env var must be identical
    on every host or the hosts compile divergent programs and hang at
    the first collective; set it from a shared launcher (train.py
    --attn_bhld) or pass bhld explicitly.
    Parameters are layout-independent (same names and shapes).
    """

    heads: int = 4
    dim_head: int = 64
    backend: str = "auto"
    dtype: Optional[Dtype] = None
    precision: Optional[jax.lax.Precision] = None
    use_bias: bool = True
    force_fp32_for_softmax: bool = True
    bhld: Optional[bool] = None
    kernel_init: Callable = kernel_init(1.0)

    @nn.compact
    def __call__(self, x: jax.Array, context: Optional[jax.Array] = None) -> jax.Array:
        spatial = x.ndim == 4
        if spatial:
            b, h, w, c = x.shape
            x = x.reshape(b, h * w, c)
        context = x if context is None else context
        bhld = (self.bhld if self.bhld is not None
                else os.environ.get("FLAXDIFF_ATTN_BHLD") == "1")
        proj = lambda name: head_projection(
            bhld, heads=self.heads, dim_head=self.dim_head,
            use_bias=self.use_bias, dtype=self.dtype,
            precision=self.precision, kernel_init=self.kernel_init,
            name=name)
        q = proj("to_q")(x)
        k = proj("to_k")(context)
        v = proj("to_v")(context)
        attend = (dot_product_attention_bhld if bhld
                  else dot_product_attention)
        out = attend(q, k, v, backend=self.backend,
                     force_fp32_for_softmax=self.force_fp32_for_softmax)
        out = head_out_projection(
            bhld, features=x.shape[-1], heads=self.heads,
            dim_head=self.dim_head, use_bias=self.use_bias,
            dtype=self.dtype, precision=self.precision,
            kernel_init=self.kernel_init)(out)
        if spatial:
            out = out.reshape(b, h, w, c)
        return out


class GEGLUFeedForward(nn.Module):
    """GEGLU-gated MLP (reference attention.py:179-238).

    With `fused` (default) the split + gelu + multiply over the packed
    [.., 2F] projection runs as one Pallas pass on TPU
    (ops/fused_adaln.py fused_geglu; FLAXDIFF_FUSED_ADALN=xla|interpret
    A/B); off-TPU the exact composition below runs."""

    dim_out: int
    mult: int = 4
    dtype: Optional[Dtype] = None
    precision: Optional[jax.lax.Precision] = None
    fused: bool = True

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        from ..ops.fused_adaln import fused_adaln_active, fused_geglu
        inner = self.dim_out * self.mult
        proj = nn.Dense(inner * 2, dtype=self.dtype, precision=self.precision,
                        name="proj_in")(x)
        if self.fused and fused_adaln_active() and proj.ndim == 3:
            x = fused_geglu(proj)
        else:
            gate, val = jnp.split(proj, 2, axis=-1)
            x = val * jax.nn.gelu(gate)
        return nn.Dense(self.dim_out, dtype=self.dtype,
                        precision=self.precision, name="proj_out")(x)


class BasicTransformerBlock(nn.Module):
    """self-attn -> cross-attn -> GEGLU FF, pre-LN (reference 240-303)."""

    heads: int = 4
    dim_head: int = 64
    backend: str = "auto"
    dtype: Optional[Dtype] = None
    precision: Optional[jax.lax.Precision] = None
    use_bias: bool = True
    force_fp32_for_softmax: bool = True
    only_pure_attention: bool = False
    use_cross_only: bool = False
    bhld: Optional[bool] = None
    kernel_init: Callable = kernel_init(1.0)

    @nn.compact
    def __call__(self, x: jax.Array, context: Optional[jax.Array] = None) -> jax.Array:
        attn = lambda name: AttentionLayer(
            heads=self.heads, dim_head=self.dim_head, backend=self.backend,
            dtype=self.dtype, precision=self.precision, use_bias=self.use_bias,
            force_fp32_for_softmax=self.force_fp32_for_softmax,
            bhld=self.bhld, kernel_init=self.kernel_init, name=name)
        ln = lambda name: nn.LayerNorm(dtype=jnp.float32, name=name)
        if self.only_pure_attention:
            return attn("attn1")(ln("norm1")(x),
                                 context if self.use_cross_only else None)
        x = x + attn("attn1")(ln("norm1")(x),
                              context if self.use_cross_only else None)
        if context is not None and not self.use_cross_only:
            x = x + attn("attn2")(ln("norm2")(x), context)
        x = x + GEGLUFeedForward(x.shape[-1], dtype=self.dtype,
                                 precision=self.precision, name="ff")(
            ln("norm3")(x))
        return x


class TransformerBlock(nn.Module):
    """Outer wrapper: optional in/out projection + residual around N basic
    blocks (reference attention.py:305-380)."""

    heads: int = 4
    dim_head: int = 64
    depth: int = 1
    backend: str = "auto"
    dtype: Optional[Dtype] = None
    precision: Optional[jax.lax.Precision] = None
    use_projection: bool = False
    use_linear_attention: bool = True  # linear (Dense) vs conv projection
    only_pure_attention: bool = False
    use_self_and_cross: bool = True
    force_fp32_for_softmax: bool = True
    bhld: Optional[bool] = None
    kernel_init: Callable = kernel_init(1.0)

    @nn.compact
    def __call__(self, x: jax.Array, context: Optional[jax.Array] = None) -> jax.Array:
        spatial = x.ndim == 4
        inner = self.heads * self.dim_head
        residual = x
        if spatial:
            b, h, w, c = x.shape
            x = x.reshape(b, h * w, c)
        else:
            c = x.shape[-1]
        if self.use_projection:
            x = nn.Dense(inner, dtype=self.dtype, precision=self.precision,
                         name="proj_in")(x)
        for i in range(self.depth):
            x = BasicTransformerBlock(
                heads=self.heads, dim_head=self.dim_head, backend=self.backend,
                dtype=self.dtype, precision=self.precision,
                force_fp32_for_softmax=self.force_fp32_for_softmax,
                only_pure_attention=self.only_pure_attention,
                use_cross_only=not self.use_self_and_cross and context is not None,
                bhld=self.bhld, kernel_init=self.kernel_init,
                name=f"block_{i}")(
                x, context=context)
        if self.use_projection:
            x = nn.Dense(c, dtype=self.dtype, precision=self.precision,
                         kernel_init=kernel_init(0.0), name="proj_out")(x)
        if spatial:
            x = x.reshape(b, h, w, c)
        return x + residual

"""Model registry: run records, per-metric best tracking, checkpoint
aliases.

Capability parity with the reference's wandb registry pipeline
(reference trainer/general_diffusion_trainer.py:560-727: push_to_registry
uploads the checkpoint as an artifact, then compares against the
sweep/project's historical best runs direction-aware and re-aliases
"best") — built on the local filesystem as the load-bearing store
(registry.json) with a wandb artifact push layered on when available, so
air-gapped training still gets registry semantics.
"""
from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, Optional


class ModelRegistry:
    """JSON-file registry of training runs and their best checkpoints.

    Layout of registry.json:
      {"runs": {run_name: {config, checkpoint_dir, step, metrics,
                           updated}},
       "best": {metric_name: {"run": ..., "value": ...,
                              "higher_is_better": ...}}}
    """

    def __init__(self, path: str):
        self.path = path
        self._data: Dict[str, Any] = {"runs": {}, "best": {}}
        if os.path.exists(path):
            with open(path) as fh:
                self._data = json.load(fh)
        self._data.setdefault("runs", {})
        self._data.setdefault("best", {})

    def _save(self):
        os.makedirs(os.path.dirname(os.path.abspath(self.path)) or ".",
                    exist_ok=True)
        # pid-unique tmp: concurrent writers (two runs finishing at once)
        # cannot clobber each other's tmp file; last replace wins whole
        tmp = f"{self.path}.tmp.{os.getpid()}"
        with open(tmp, "w") as fh:
            json.dump(self._data, fh, indent=2, sort_keys=True)
        os.replace(tmp, self.path)

    # -- write ---------------------------------------------------------------
    def register_run(self, name: str, checkpoint_dir: str, step: int,
                     metrics: Dict[str, float],
                     metric_directions: Optional[Dict[str, bool]] = None,
                     config: Optional[Dict[str, Any]] = None
                     ) -> Dict[str, bool]:
        """Record/update a run; returns {metric: became_best} — the
        reference's is-this-the-best-run comparison
        (general_diffusion_trainer.py:596-703), direction-aware via
        `metric_directions` ({name: higher_is_better}, default lower)."""
        run = self._data["runs"].setdefault(name, {})
        run.update({
            "checkpoint_dir": checkpoint_dir,
            "step": int(step),
            "metrics": {k: float(v) for k, v in metrics.items()},
            "updated": time.time(),
        })
        if config is not None:
            run["config"] = config

        # persist directions, then RECOMPUTE best from all runs' current
        # metrics — a run re-registering with a worse value must not keep
        # holding "best" with a stale value whose checkpoint has rotated
        # away (max_to_keep).
        dirs = self._data.setdefault("directions", {})
        for metric, hib in (metric_directions or {}).items():
            dirs[metric] = bool(hib)
        self._recompute_best()
        became_best = {m: self._data["best"].get(m, {}).get("run") == name
                       for m in metrics}
        self._save()
        return became_best

    def _recompute_best(self):
        dirs = self._data.get("directions", {})
        best: Dict[str, Any] = {}
        for name, run in self._data["runs"].items():
            for metric, value in run.get("metrics", {}).items():
                hib = bool(dirs.get(metric, False))
                cur = best.get(metric)
                if (cur is None or (value > cur["value"] if hib
                                    else value < cur["value"])):
                    best[metric] = {
                        "run": name, "value": float(value),
                        "higher_is_better": hib,
                        "checkpoint_dir": run["checkpoint_dir"],
                        "step": int(run["step"]),
                    }
        self._data["best"] = best

    def push_artifact(self, name: str, checkpoint_dir: str) -> bool:
        """Upload the checkpoint directory as a wandb artifact when wandb
        is importable and a run is active (reference
        general_diffusion_trainer.py:560-594) — the artifact lands in the
        active run's project; returns False offline."""
        try:
            import wandb
            if wandb.run is None:
                return False
            art = wandb.Artifact(name.replace("/", "_"), type="model")
            art.add_dir(checkpoint_dir)
            wandb.run.log_artifact(art, aliases=["latest"])
            return True
        except Exception:
            return False

    # -- read ----------------------------------------------------------------
    def runs(self) -> Dict[str, Any]:
        return dict(self._data["runs"])

    def top_k(self, metric: str, k: int = 5):
        """Ranked top-k runs for `metric` with their run metadata — the
        reference compares a finishing run against the sweep/project's
        historical top-k (general_diffusion_trainer.py:596-703).
        Direction-aware via the persisted metric directions."""
        hib = bool(self._data.get("directions", {}).get(metric, False))
        ranked = []
        for name, run in self._data["runs"].items():
            if metric in run.get("metrics", {}):
                ranked.append({
                    "run": name,
                    "value": float(run["metrics"][metric]),
                    "step": int(run.get("step", 0)),
                    "checkpoint_dir": run.get("checkpoint_dir"),
                    "config": run.get("config"),
                    "higher_is_better": hib,
                })
        ranked.sort(key=lambda r: r["value"], reverse=hib)
        return ranked[:k]

    def best_run(self, metric: str) -> Optional[Dict[str, Any]]:
        return self._data["best"].get(metric)

    def best_checkpoint(self, metric: str) -> Optional[str]:
        best = self.best_run(metric)
        return best["checkpoint_dir"] if best else None


def pull_artifact(name: str, target_dir: str,
                  alias: str = "latest") -> Optional[str]:
    """Download the model artifact `name` into `target_dir` from the
    ACTIVE wandb run's project — the resume half of push_artifact
    (reference simple_trainer.py:194-211: on wandb run resume, the logged
    model artifact is auto-downloaded and training restores from it).
    Returns the local directory, or None when wandb is unavailable, no
    run is active, or no such artifact exists."""
    try:
        import wandb
        if wandb.run is None:
            return None
        art = wandb.run.use_artifact(
            f"{name.replace('/', '_')}:{alias}", type="model")
        return art.download(root=target_dir)
    except Exception:
        return None


def compare_against_wandb_best(current_value: float,
                               metric: str = "train/best_loss",
                               top_k: int = 2,
                               higher_is_better: bool = False,
                               api: Any = None,
                               entity: Optional[str] = None,
                               project: Optional[str] = None,
                               sweep_id: Optional[str] = None,
                               filters: Optional[Dict[str, Any]] = None,
                               exclude_run_id: Optional[str] = None):
    """Compare a finishing run against the wandb project's (or sweep's)
    historical best — the API variant of the local registry's top_k
    (reference general_diffusion_trainer.py:596-703 semantics).

    Ranks the fetched runs by `summary["best_<metric>"]` (project query)
    or `summary[<metric>]` (sweep query, matching the reference's two
    paths), direction-aware; takes the top-k slice's value bounds; and
    returns (is_good, is_best, bounds, ranked_top_k) where is_good means
    the current run lands inside the top-k bounds and is_best means it
    beats them all.

    `api` is injectable (duck-typed: `.runs(path=..., filters=...)` and
    `.sweep(path).runs`, each run carrying `.id` and `.summary`), so the
    logic is testable without network; None lazily builds `wandb.Api()`.
    Returns (True, True, None, []) when there is no history to compare
    against — a first run is trivially the best, as in the local
    registry. Runs without a finite value for the metric (crashed runs
    never wrote the summary key) are dropped before ranking — the
    reference ranks them at ±inf, which blows out the bounds and makes
    is_good vacuously true. Pass `exclude_run_id` with the finishing
    run's own id: wandb syncs summaries live, so the run under
    evaluation otherwise appears in its own history and a new project
    best would compare against itself and report is_best=False.
    """
    import math
    if api is None:
        import wandb
        api = wandb.Api()
    if sweep_id is not None:
        if filters is not None:
            raise ValueError(
                "filters only apply to the project query; the sweep API "
                "exposes no server-side filtering — filter the sweep's "
                "runs yourself or drop sweep_id")
        runs = list(api.sweep(f"{entity}/{project}/{sweep_id}").runs)
        key = metric
    else:
        runs = list(api.runs(path=f"{entity}/{project}", filters=filters))
        key = f"best_{metric}"

    def val(run):
        v = run.summary.get(key)
        return float(v) if isinstance(v, (int, float)) else float("nan")

    runs = [r for r in runs
            if math.isfinite(val(r))
            and getattr(r, "id", None) != exclude_run_id]
    runs = sorted(runs, key=val, reverse=higher_is_better)
    top = runs[:top_k]
    if not top:
        return True, True, None, []
    vals = [val(r) for r in top]
    bounds = (min(vals), max(vals))
    if higher_is_better:
        is_good = current_value > bounds[0]
        is_best = current_value > bounds[1]
    else:
        is_good = current_value < bounds[1]
        is_best = current_value < bounds[0]
    ranked = [{"run": getattr(r, "id", None), "value": val(r)}
              for r in top]
    return is_good, is_best, bounds, ranked

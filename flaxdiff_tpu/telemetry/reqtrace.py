"""Request-scoped tracing for the serving layer (docs/OBSERVABILITY.md).

The serving histograms (`serving/{latency,queue,compile,device}_ms`)
answer "how is the fleet doing" in aggregate; this module answers the
attribution question they cannot: *follow one `SampleRequest`* from
submit through admission, queue wait, every micro-batch round it rode
(with the compiled program's cache key, batch bucket, live-step counts,
and cache-plan step codes), terminal denoise, and completion — the
decomposition a multi-level split across chips (FastUSP-style) needs
before any cross-chip placement decision is measurable.

Cost contract (enforced by a counting-mock test): tracing is HOST-side
bookkeeping only. Every timestamp is `time.perf_counter()` taken on the
dispatch/completion threads at points the scheduler already timestamps;
no device value is read, and the blessed `_block_until_ready` /
`_device_get` seams are called exactly as often as in an untraced run.
On the disabled hub (`Telemetry.recorder is None`) every call is a
cheap no-op returning None.

Output, per traced request:

- Chrome trace-event spans in the hub's `TraceRecorder` (`trace.json`,
  Perfetto-loadable): a `req.queue` span (submit -> first dispatch) and
  a `req.serve` span (first dispatch -> samples on host) on a per-trace
  lane, plus shared `serve.round` / `serve.finalize` spans on the
  dispatch lane carrying program key / bucket / rows / step codes.
- One `request_trace` JSONL row in `telemetry.jsonl` with the same
  latency decomposition the result future carries — the row's
  `queue_ms + compile_ms + device_ms == latency_ms` identity is exact
  by construction (all four derive from the same three timestamps), so
  per-request rows reconcile with the aggregate histograms to within
  timer resolution (tested).

`scripts/diagnose_run.py` renders the stream as a "Request traces"
section (per-span p50/p99 + slowest-trace drill-down).
"""
from __future__ import annotations

import itertools
import os
from typing import Any, Dict, List, Optional

# Chrome-trace lane ids: rounds/finalize on one fixed dispatch lane,
# each request on its own small lane so Perfetto stacks them readably.
DISPATCH_TID = 900_000
_REQ_TID_BASE = 100_000
_REQ_TID_SPAN = 100_000


class RequestTrace:
    """Host-side accumulator for one request's trace (cheap: a list of
    dicts appended by the dispatch thread, emitted once at completion)."""

    __slots__ = ("trace_id", "seq", "submit_s", "summary", "rounds",
                 "outcome", "events", "hop", "spans", "tid_fixed")

    def __init__(self, trace_id: str, seq: int, submit_s: float,
                 summary: Dict[str, Any], hop: str = "req",
                 tid_fixed: Optional[int] = None):
        self.trace_id = trace_id
        self.seq = seq
        self.submit_s = submit_s
        self.summary = summary
        # which hop of the serving path emitted this trace ("door",
        # "r0", ... ). A propagated trace (see RequestTracer.begin
        # `parent`) keeps the MINTING hop's trace id and lane but its
        # own hop label, so one Chrome lane carries door + replica
        # spans for the same request, each attributable.
        self.hop = hop
        self.tid_fixed = tid_fixed
        self.rounds: List[Dict[str, Any]] = []
        # recovery events (round_fault/requeued/quarantined/rebuild/
        # brownout, serving/supervision.py) — kept separate from
        # `rounds` so round_detail still counts dispatched rounds 1:1
        self.events: List[Dict[str, Any]] = []
        # door phase spans (RequestTracer.hop_span): exact segments of
        # the door timeline whose per-name sums land in the row's
        # `phase_ms` and reconcile with latency_ms by construction
        self.spans: List[Dict[str, Any]] = []
        self.outcome: Optional[str] = None

    @property
    def tid(self) -> int:
        if self.tid_fixed is not None:
            return self.tid_fixed
        return _REQ_TID_BASE + (self.seq % _REQ_TID_SPAN)


def _phase_sums(spans: List[Dict[str, Any]]) -> Dict[str, float]:
    """Per-span-name millisecond sums, UNROUNDED — the reconciliation
    identity (non-hedge phases sum to latency_ms) must survive into
    the JSONL row exactly as constructed."""
    out: Dict[str, float] = {}
    for s in spans:
        out[s["span"]] = out.get(s["span"], 0.0) + s["ms"]
    return dict(sorted(out.items()))


def _req_summary(req) -> Dict[str, Any]:
    return {
        "sampler": str(getattr(req, "sampler", "?")),
        "nfe": int(getattr(req, "diffusion_steps", 0)),
        "resolution": int(getattr(req, "resolution", 0)),
        "num_samples": int(getattr(req, "num_samples", 0)),
        "guidance": float(getattr(req, "guidance_scale", 0.0)),
        "seed": int(getattr(req, "seed", 0)),
    }


class RequestTracer:
    """Mints trace ids at submit and emits per-request spans + JSONL
    rows through the telemetry hub. All methods no-op (and `begin`
    returns None) when the hub has no trace recorder, so the scheduler
    carries the tracer unconditionally."""

    def __init__(self, telemetry, prefix: str = "req"):
        # `prefix` namespaces the minted trace ids: the front door and
        # each replica scheduler carry their OWN tracer over one shared
        # hub, and a door-level trace must never collide with a
        # replica-level one for the same request
        self.telemetry = telemetry
        self.prefix = prefix
        self._seq = itertools.count()
        self._pid = os.getpid()

    @property
    def enabled(self) -> bool:
        return (self.telemetry is not None
                and self.telemetry.recorder is not None)

    def context(self, tr: Optional[RequestTrace]
                ) -> Optional[Dict[str, Any]]:
        """Portable trace context for cross-hop propagation: what the
        front door hands `Replica.submit` so the replica scheduler's
        spans join the door-minted trace (same id, same Chrome lane)."""
        if tr is None:
            return None
        return {"trace_id": tr.trace_id, "tid": tr.tid}

    # -- lifecycle ----------------------------------------------------------
    def begin(self, req, submit_s: float,
              parent: Optional[Dict[str, Any]] = None
              ) -> Optional[RequestTrace]:
        """Mint a trace at submit time; None on a disabled hub. With
        `parent` (a `context()` dict propagated from an upstream hop)
        the trace ADOPTS the parent's id and lane instead of minting —
        one trace id then spans front door -> replica -> serving
        rounds, and every span stays attributable via its `hop` arg."""
        if not self.enabled:
            return None
        seq = next(self._seq)
        if parent is not None:
            tr = RequestTrace(str(parent["trace_id"]), seq, submit_s,
                              _req_summary(req), hop=self.prefix,
                              tid_fixed=parent.get("tid"))
        else:
            tr = RequestTrace(f"{self.prefix}-{self._pid}-{seq}", seq,
                              submit_s, _req_summary(req),
                              hop=self.prefix)
        self.telemetry.recorder.instant_at(
            "req.submit", submit_s, cat="serving",
            args={"trace_id": tr.trace_id, "hop": tr.hop,
                  **tr.summary}, tid=tr.tid)
        return tr

    def shed(self, tr: Optional[RequestTrace], reason: str,
             at_s: float) -> None:
        """A request dropped before compute (deadline, queue-full, bad
        request): close its trace with the shed outcome so the timeline
        shows WHERE admission lost it."""
        if tr is None or not self.enabled:
            return
        tr.outcome = f"shed:{reason}"
        rec = self.telemetry.recorder
        rec.event_at("req.queue", tr.submit_s, at_s, cat="serving",
                     args={"trace_id": tr.trace_id,
                           "outcome": tr.outcome}, tid=tr.tid)
        self.telemetry.write_record({
            "type": "request_trace", "trace_id": tr.trace_id,
            "hop": tr.hop, "outcome": tr.outcome,
            "queue_ms": (at_s - tr.submit_s) * 1e3, **tr.summary})

    def note(self, tr: Optional[RequestTrace], kind: str, at_s: float,
             **args) -> None:
        """Attach one recovery event (retry/quarantine/brownout/
        rebuild-interrupt, serving/supervision.py) to a request's
        trace: an instant on the request's lane plus a row in the
        trace's `recovery` list, so every recovery step is attributable
        in the drill-down."""
        if tr is None or not self.enabled:
            return
        tr.events.append({"event": kind, **args})
        self.telemetry.recorder.instant_at(
            f"req.{kind}", at_s, cat="serving",
            args={"trace_id": tr.trace_id, **args}, tid=tr.tid)

    def fail(self, state, outcome: str, at_s: float) -> None:
        """A request resolved with a typed fault (ServingFault): close
        its trace with the fault outcome, same row shape as `shed` but
        carrying the attempt count and recovery events."""
        tr = getattr(state, "trace", None)
        if tr is None or not self.enabled:
            return
        tr.outcome = outcome
        rec = self.telemetry.recorder
        rec.event_at("req.queue", tr.submit_s, at_s, cat="serving",
                     args={"trace_id": tr.trace_id,
                           "outcome": outcome}, tid=tr.tid)
        row = {"type": "request_trace", "trace_id": tr.trace_id,
               "hop": tr.hop, "outcome": outcome,
               "queue_ms": (at_s - tr.submit_s) * 1e3,
               "attempts": int(getattr(state, "attempts", 0)),
               **tr.summary}
        if tr.spans:
            row["phase_ms"] = _phase_sums(tr.spans)
        if tr.events:
            row["recovery"] = list(tr.events)
        self.telemetry.write_record(row)

    def hop_span(self, tr: Optional[RequestTrace], name: str,
                 t0_s: float, t1_s: float, **args) -> None:
        """One door-phase span (`door.route` / `door.attempt` /
        `door.failover` / `door.hedge`) on the request's lane. The
        front door closes these at timestamps SHARED with the next
        segment's open (and with the delivery timestamp that feeds the
        `frontdoor/latency_ms` histogram), so the non-overlapping
        phases tile [submit, delivery] exactly and the row's `phase_ms`
        sums reconcile with latency_ms by construction. `door.hedge`
        is the one overlapping span (a concurrent arm) — reported, but
        excluded from the tiling identity."""
        if tr is None or not self.enabled:
            return
        tr.spans.append({"span": name, "ms": (t1_s - t0_s) * 1e3,
                         **args})
        self.telemetry.recorder.event_at(
            name, t0_s, t1_s, cat="serving",
            args={"trace_id": tr.trace_id, **args}, tid=tr.tid)

    def rebuild(self, t0_s: float, t1_s: float,
                args: Optional[Dict[str, Any]] = None) -> None:
        """Engine supervision span on the dispatch lane: drain +
        rebuild + prewarm after device loss."""
        if not self.enabled:
            return
        self.telemetry.recorder.event_at(
            "serve.rebuild", t0_s, t1_s, cat="serving",
            args=args or {}, tid=DISPATCH_TID)

    # -- dispatch-side spans (dispatch thread; host timestamps only) --------
    def round(self, rows, info: Optional[Dict[str, Any]], t0: float,
              t1: float, round_no: int) -> None:
        """One micro-batch round: ONE shared `serve.round` span on the
        dispatch lane + a per-participating-request round record (the
        same dict, it is immutable once emitted) for the drill-down."""
        if not self.enabled:
            return
        detail: Dict[str, Any] = {"round": int(round_no),
                                  "ms": round((t1 - t0) * 1e3, 3)}
        if info:
            detail.update(info)
        self.telemetry.recorder.event_at(
            "serve.round", t0, t1, cat="serving", args=detail,
            tid=DISPATCH_TID)
        for r in rows:
            tr = getattr(r, "trace", None)
            if tr is not None:
                tr.rounds.append(detail)

    def finalize(self, rows, info: Optional[Dict[str, Any]], t0: float,
                 t1: float) -> None:
        """Terminal denoise + decode of the rows that completed."""
        if not self.enabled:
            return
        detail: Dict[str, Any] = {"ms": round((t1 - t0) * 1e3, 3),
                                  "rows": len(rows)}
        if info:
            detail.update(info)
        self.telemetry.recorder.event_at(
            "serve.finalize", t0, t1, cat="serving", args=detail,
            tid=DISPATCH_TID)

    # -- completion (completion thread, after the blessed host sync) --------
    def complete(self, state, queue_ms: float, compile_ms: float,
                 device_ms: float, latency_ms: float,
                 ready_s: float) -> None:
        """Emit the request's spans and its `request_trace` JSONL row.
        Called with the SAME decomposition the `SampleResult` carries,
        so per-request rows sum exactly to what the serving histograms
        observed."""
        tr = getattr(state, "trace", None)
        if tr is None or not self.enabled:
            return
        tr.outcome = "ok"
        first_dispatch_s = tr.submit_s + queue_ms / 1e3
        rec = self.telemetry.recorder
        rec.event_at("req.queue", tr.submit_s, first_dispatch_s,
                     cat="serving",
                     args={"trace_id": tr.trace_id}, tid=tr.tid)
        rec.event_at("req.serve", first_dispatch_s, ready_s,
                     cat="serving",
                     args={"trace_id": tr.trace_id, "hop": tr.hop,
                           "compile_ms": round(compile_ms, 3),
                           "device_ms": round(device_ms, 3),
                           "rounds": int(state.rounds)}, tid=tr.tid)
        row = {
            "type": "request_trace", "trace_id": tr.trace_id,
            "hop": tr.hop, "outcome": "ok",
            "queue_ms": queue_ms, "compile_ms": compile_ms,
            "device_ms": device_ms, "latency_ms": latency_ms,
            "rounds": int(state.rounds),
            "round_detail": list(tr.rounds), **tr.summary}
        if tr.spans:
            row["phase_ms"] = _phase_sums(tr.spans)
        # recovery provenance (serving/supervision.py): retried or
        # degraded completions say so in their own row
        attempts = int(getattr(state, "attempts", 0))
        if attempts:
            row["attempts"] = attempts
        degraded = tuple(getattr(state, "degraded", ()) or ())
        if degraded:
            row["degraded"] = list(degraded)
        if tr.events:
            row["recovery"] = list(tr.events)
        self.telemetry.write_record(row)

#!/bin/bash
# r5 patient prober: long-timeout probe every 15 min; on the first
# healthy answer run the FULL hardware session (scripts/hw_session.py,
# info-value stage order) instead of the budget-bounded driver bench.
# Rationale for the cadence: killed-mid-init clients leak a server-side
# lease for ~10-20 min, so sparse patient probes beat churn.
set -u
OUT=${1:-r5_hw_session.jsonl}
DEADLINE=$(( $(date +%s) + ${2:-36000} ))   # default: give up after 10 h

cd "$(dirname "$0")/.."

while [ "$(date +%s)" -lt "$DEADLINE" ]; do
  if timeout 560 python - <<'PYEOF'
import jax, sys
sys.exit(0 if jax.devices()[0].platform == "tpu" else 1)
PYEOF
  then
    echo "$(date -u +%FT%TZ) tunnel healthy; starting hw session" >&2
    exec python scripts/hw_session.py "$OUT" 1785547800 >> hw_session_r5.out 2>&1
  fi
  echo "$(date -u +%FT%TZ) tunnel still wedged; sleeping 900s" >&2
  sleep 900
done
echo "$(date -u +%FT%TZ) gave up waiting for the tunnel" >&2

"""One CLI for the whole static-analysis suite.

    python scripts/lint.py                  # everything, text report
    python scripts/lint.py --json           # stable machine output
    python -m flaxdiff_tpu.analysis         # same tool
    python scripts/lint.py --rules host-sync,silent-except --no-graph
    python scripts/lint.py --root some/tree --rules silent-except
    python scripts/lint.py --tighten        # rewrite budgets.py down
                                            # to the observed counts

Exit code 0 = every rule within its allowlist budget; 1 = over-budget
findings (printed to stderr). `--json` prints ONE json object to
stdout, byte-stable across runs on an unchanged tree (sorted keys,
sorted findings, no timestamps or absolute paths) — diff two runs to
diff the findings. `--root` scans a custom file/tree with EMPTY
allowlists and rule dir-scoping dropped (fixture mode — the contract
the old standalone scripts/check_*.py gates had); graph rules are
skipped there because they audit traced programs, not files.
`--tighten` (analysis/tighten.py) shrinks every slack budget in
budgets.py to its observed count — acting on the report's shrink notes
is one command, never a hand-edit.
"""
from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="lint",
        description="flaxdiff_tpu graph-hygiene analyzer "
                    "(docs/ANALYSIS.md)")
    ap.add_argument("--json", action="store_true",
                    help="stable machine-readable report on stdout")
    ap.add_argument("--root", default=None,
                    help="scan this file/tree with EMPTY allowlists "
                         "and dir scoping dropped (fixture mode); "
                         "default: the repo's production roots with "
                         "the central allowlist")
    ap.add_argument("--docs", default=None,
                    help="metric reference markdown for the "
                         "metric-name rule (default: "
                         "docs/OBSERVABILITY.md)")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule ids to run "
                         "(default: all)")
    ap.add_argument("--no-graph", action="store_true",
                    help="skip the jaxpr analyzers (pure-AST run, no "
                         "jax import)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalogue and exit")
    ap.add_argument("--tighten", action="store_true",
                    help="rewrite budgets.py: every slack budget "
                         "shrinks to its observed count (only rules "
                         "that ran are touched)")
    ap.add_argument("--tighten-out", default=None, metavar="PATH",
                    help="write the tightened budgets module here "
                         "instead of flaxdiff_tpu/analysis/budgets.py")
    args = ap.parse_args(argv)

    from . import framework

    if args.list_rules:
        from . import ast_rules  # noqa: F401 — registers
        if not args.no_graph:
            from . import graph_rules  # noqa: F401 — registers
            from . import shard_rules  # noqa: F401 — registers
        for rid, rule in sorted(framework.all_rules().items()):
            print(f"{rid:20s} {rule.doc}  [{rule.docs}]")
        return 0

    if not args.no_graph and args.root is None:
        # the graph rules trace programs: never let lint grab a real
        # accelerator, and force the virtual multi-device host platform
        # the MESHED inventory needs. Both are harmless if a backend
        # already initialized (the in-process tier-1 tests pin the same
        # environment in conftest.py).
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()

    rule_ids = ([r.strip() for r in args.rules.split(",") if r.strip()]
                if args.rules else None)
    report = framework.run(rule_ids=rule_ids, root=args.root,
                           docs_path=args.docs,
                           with_graph=not args.no_graph)

    if args.tighten:
        from .tighten import render_budgets, tightened_budgets
        new_allow, new_up, new_comm, changes = tightened_budgets(
            report, framework.ALLOWLIST, framework.UPCAST_BUDGET,
            framework.COMM_BUDGET)
        out_path = args.tighten_out or os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "budgets.py")
        with open(out_path, "w", encoding="utf-8") as f:
            f.write(render_budgets(new_allow, new_up, new_comm))
        for line in changes:
            print(f"tightened: {line}")
        print(f"{'wrote' if changes else 'no slack; rewrote'} "
              f"{out_path} ({len(changes)} budget(s) tightened)")
        if not report.ok:
            print("over-budget findings remain — tighten never raises "
                  "a budget; fix or hand-edit deliberately:",
                  file=sys.stderr)
            for fnd in sorted(report.failures):
                print(fnd.render(), file=sys.stderr)
        return 0 if report.ok else 1

    if args.json:
        print(framework.stable_json(report))
    else:
        report.render_text()
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())

"""Data pipeline (capability parity: reference flaxdiff/data/).

Layers: sources (indexable record access) -> augmenters (per-sample
transforms) -> grain loader assembly (sharded, multi-worker, collated)
-> host-numpy batch iterators consumed by DiffusionTrainer.put_batch.
The online HTTP streaming loader mirrors reference data/online_loader.py
with an injectable fetcher so it is testable offline.
"""
from .dataloaders import get_dataset_grain, make_batch_iterator
from .dataplane import (
    BatchScreen,
    BreakerBoard,
    DataPlane,
    HedgedFetcher,
    QuarantineJournal,
    ResumableStream,
    SourceBreaker,
    StarvationLadder,
    batch_digest,
)
from .dataset_map import DATASET_REGISTRY, get_dataset, register_dataset
from .online_loader import OnlineStreamingDataLoader
from .sources.base import DataAugmenter, DataSource, MediaDataset
from .sources.images import (
    ImageAugmenter,
    MemoryImageSource,
    prompt_templates_for_class,
)
from .sources.videos import VideoClipAugmenter, VideoFolderSource

__all__ = [
    "DataSource",
    "DataAugmenter",
    "MediaDataset",
    "MemoryImageSource",
    "ImageAugmenter",
    "prompt_templates_for_class",
    "VideoFolderSource",
    "VideoClipAugmenter",
    "get_dataset_grain",
    "make_batch_iterator",
    "OnlineStreamingDataLoader",
    "DataPlane",
    "ResumableStream",
    "QuarantineJournal",
    "BreakerBoard",
    "SourceBreaker",
    "HedgedFetcher",
    "StarvationLadder",
    "BatchScreen",
    "batch_digest",
    "DATASET_REGISTRY",
    "get_dataset",
    "register_dataset",
]

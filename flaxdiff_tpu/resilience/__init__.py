"""Resilience layer: deterministic fault injection, unified retry/
backoff, checkpoint-integrity tooling, and a train-loop watchdog.

At pod scale preemptions, flaky object stores, and wedged loaders are
the steady state (ROADMAP north star; Pulse arXiv:2606.19163 treats
elasticity as first-class). This package centralizes what used to be
ad-hoc per-module handling:

  events        structured resilience-event log (counters + subscribers),
                surfaced through trainer/logging.py to JSONL/wandb/stdout
  faults        seedable `FaultPlan` arming named sites (ckpt.save,
                data.fetch, step.nan, ...) — chaos runs replay in pytest
  retry         `RetryPolicy`: exponential backoff, jitter, deadline,
                non-retryable classification
  watchdog      heartbeat thread turning hangs into checkpoint-and-exit
  verify        offline checkpoint-integrity checker (+ chaos corruption
                helper); CLI in scripts/verify_checkpoint.py
  coordination  multi-host restart as ONE consensus event: step-ledger
                two-phase checkpoint commits, consensus restore, crash
                barriers with deadlines (docs/RESILIENCE.md)
  elastic       LIVE world membership on top of coordination:
                shrink-to-survive after a lost host, mid-run
                re-admission of replacement hosts, pod anomaly quorums
                — plus MemberTransport, which re-scopes the commit
                rounds to the current member set across transitions

Dependency direction: trainer/ and data/ import resilience; resilience
imports neither (verify's deep check lazily uses the Checkpointer).
"""
from .coordination import (
    BarrierTimeout,
    ConsensusError,
    CoordinationError,
    FileTransport,
    InMemoryTransport,
    JaxDistributedTransport,
    RestartCoordinator,
    StepLedger,
    Transport,
    agree_epoch,
    default_transport,
)
from .elastic import (
    ElasticConfig,
    ElasticError,
    ElasticWorldManager,
    MemberTransport,
    QuorumDecision,
    WorldChange,
    WorldView,
)
from .events import (
    EventLog,
    ResilienceEvent,
    global_event_log,
    record_event,
    set_global_event_log,
    use_event_log,
)
from .faults import (
    FaultPlan,
    FaultSpec,
    InjectedFault,
    InjectedHTTPError,
    active_plan,
    install_plan,
)
from .faults import check as fault_check
from .faults import maybe_stall as fault_stall
from .retry import RetryError, RetryPolicy, default_classifier
from .verify import corrupt_step_dir, verify_checkpoint, verify_step
from .watchdog import Watchdog

__all__ = [
    "EventLog",
    "ResilienceEvent",
    "global_event_log",
    "set_global_event_log",
    "use_event_log",
    "record_event",
    "FaultPlan",
    "FaultSpec",
    "InjectedFault",
    "InjectedHTTPError",
    "active_plan",
    "install_plan",
    "fault_check",
    "fault_stall",
    "RetryPolicy",
    "RetryError",
    "default_classifier",
    "Watchdog",
    "verify_checkpoint",
    "verify_step",
    "corrupt_step_dir",
    "CoordinationError",
    "BarrierTimeout",
    "ConsensusError",
    "StepLedger",
    "Transport",
    "InMemoryTransport",
    "JaxDistributedTransport",
    "FileTransport",
    "RestartCoordinator",
    "agree_epoch",
    "default_transport",
    "ElasticConfig",
    "ElasticError",
    "ElasticWorldManager",
    "MemberTransport",
    "QuorumDecision",
    "WorldChange",
    "WorldView",
]

"""Cross-host metric aggregation over the resilience `Transport`.

Per-host metrics answer "how is MY host doing"; at pod scale the
actionable question is skew — one slow host sets the pace of every
collective. This module gathers each host's scalar metrics dict over
the PR-2 `Transport` abstraction (`JaxDistributedTransport` on real
pods, `InMemoryTransport` in CPU tests — the exact same protocol) and
reduces them to min/max/mean/p50/p99 (+ relative spread) per metric, so
process 0 can log pod-wide figures like `pod/step_time/max` and the
skew between stragglers and the median.

The gather is a COLLECTIVE: every host must call `aggregate` the same
number of times at the same points (the trainer calls it at log
cadence, which SPMD driver code reaches in lockstep — the same
assumption the commit rounds make). A missed deadline raises the
transport's BarrierTimeout; the Telemetry hub catches it and disables
further aggregation rather than letting metrics kill a run.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np


class CrossHostAggregator:
    """Stateless reducer over a Transport's `allgather_json`; only the
    round sequence number is local state (it namespaces the gather keys
    so rounds can never cross-read)."""

    def __init__(self, transport, timeout: float = 60.0):
        self.transport = transport
        self.timeout = timeout
        self._seq = 0

    @property
    def process_index(self) -> int:
        return self.transport.process_index

    @property
    def world_size(self) -> int:
        return self.transport.process_count

    def aggregate(self, metrics: Dict[str, float]
                  ) -> Dict[str, Dict[str, float]]:
        """Gather every host's `{name: float}` dict; returns
        `{name: {min, max, mean, p50, p99, spread, hosts}}` computed
        identically on every host. Metrics missing on some hosts are
        reduced over the hosts that reported them."""
        seq, self._seq = self._seq, self._seq + 1
        clean = {str(k): float(v) for k, v in metrics.items()
                 if v is not None and np.isfinite(v)}
        gathered: List[Dict[str, float]] = self.transport.allgather_json(
            f"telemetry.agg.{seq}", clean, self.timeout)
        names = sorted({k for d in gathered if isinstance(d, dict)
                        for k in d})
        out: Dict[str, Dict[str, float]] = {}
        for name in names:
            vals = np.asarray([d[name] for d in gathered
                               if isinstance(d, dict) and name in d],
                              dtype=np.float64)
            if vals.size == 0:
                continue
            mean = float(vals.mean())
            stats = {
                "min": float(vals.min()),
                "max": float(vals.max()),
                "mean": mean,
                "p50": float(np.percentile(vals, 50)),
                "p99": float(np.percentile(vals, 99)),
                "hosts": float(vals.size),
            }
            # relative straggler spread: (max - min) / mean — the number
            # to alarm on (0 on a world of one)
            stats["spread"] = ((stats["max"] - stats["min"]) / mean
                               if mean != 0 else 0.0)
            out[name] = stats
        return out

    @staticmethod
    def flatten(stats: Dict[str, Dict[str, float]],
                prefix: str = "pod") -> Dict[str, float]:
        """`{"pod/<metric>/<stat>": value}` for exporter snapshots."""
        return {f"{prefix}/{name}/{stat}": v
                for name, per in stats.items() for stat, v in per.items()}

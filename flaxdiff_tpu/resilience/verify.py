"""Offline checkpoint-integrity checking (and the deliberate-corruption
helper the chaos suite uses to manufacture broken checkpoints).

A corrupt orbax step dir is indistinguishable from a good one at the
`all_steps()` level — the step is listed, `latest_step()` returns it,
and only an actual restore attempt raises (observed: truncated
`_METADATA` -> JSONDecodeError; missing chunk files -> FileNotFoundError).
`verify_checkpoint` front-loads that discovery so an operator can audit
a checkpoint directory before pointing a 256-chip job at it.

Check levels:
  shallow  structure only: step dir present, completion metadata
           (`_CHECKPOINT_METADATA`) present, `state` item dir non-empty,
           no zero-byte files, item metadata parseable.
  deep     additionally restores every leaf to host numpy (topology-free
           OCDBT read) and reports leaf count/bytes + non-finite leaves.
"""
from __future__ import annotations

import dataclasses
import os
from typing import Dict, List, Optional

COMPLETION_MARKER = "_CHECKPOINT_METADATA"


@dataclasses.dataclass
class StepReport:
    step: int
    ok: bool
    errors: List[str] = dataclasses.field(default_factory=list)
    n_files: int = 0
    n_bytes: int = 0
    n_leaves: Optional[int] = None          # deep only
    nonfinite_leaves: List[str] = dataclasses.field(default_factory=list)
    # ledger commit status (None = no ledger present / not annotated).
    # Validity and commitment are orthogonal: an intact-but-uncommitted
    # step is still not restorable under coordination.
    committed: Optional[bool] = None

    def as_dict(self) -> Dict[str, object]:
        return dataclasses.asdict(self)


def annotate_ledger(directory: str, reports: List[StepReport]) -> Dict:
    """Attach per-step ledger commit status to `reports` and return a
    summary dict ({present, path, committed_steps, entries,
    world_changes, quorum_decisions}) for the fleet-debugging CLI —
    elastic membership transitions round-trip through the `--json`
    report so a fleet diff shows WHICH world committed each step. With
    no ledger file every `committed` stays None (pre-coordination
    checkpoint dir)."""
    from .coordination import StepLedger
    ledger = StepLedger(directory)
    if not ledger.exists():
        return {"present": False, "path": ledger.path,
                "committed_steps": [], "entries": 0,
                "world_changes": [], "quorum_decisions": []}
    committed = set(ledger.committed_steps())
    for r in reports:
        if r.step >= 0:
            r.committed = r.step in committed
    return {"present": True, "path": ledger.path,
            "committed_steps": sorted(committed),
            "entries": len(ledger.entries()),
            "world_changes": ledger.world_changes(),
            "quorum_decisions": ledger.quorum_decisions()}


def _step_dir(directory: str, step: int) -> str:
    # orbax lays out `<dir>/<step>/` (no padding by default)
    return os.path.join(directory, str(step))


def _scan_files(root: str, report: StepReport) -> None:
    for dirpath, _, files in os.walk(root):
        for f in files:
            p = os.path.join(dirpath, f)
            try:
                size = os.path.getsize(p)
            except OSError as e:
                report.errors.append(f"unreadable file {p}: {e}")
                continue
            report.n_files += 1
            report.n_bytes += size
            if size == 0:
                report.errors.append(
                    f"zero-byte file (truncated write?): "
                    f"{os.path.relpath(p, root)}")


def verify_step(directory: str, step: int, deep: bool = False) -> StepReport:
    """Integrity-check one step dir; never raises on corruption — the
    report carries the errors."""
    report = StepReport(step=step, ok=True)
    sdir = _step_dir(directory, step)
    if not os.path.isdir(sdir):
        report.ok = False
        report.errors.append(f"step directory missing: {sdir}")
        return report
    if not os.path.exists(os.path.join(sdir, COMPLETION_MARKER)):
        report.errors.append(
            f"no {COMPLETION_MARKER} — save may not have completed")
    state_dir = os.path.join(sdir, "state")
    if not os.path.isdir(state_dir) or not os.listdir(state_dir):
        report.errors.append("state item missing or empty")
    _scan_files(sdir, report)
    if deep and not report.errors:
        _deep_check(directory, step, report)
    report.ok = not report.errors
    return report


def _deep_check(directory: str, step: int, report: StepReport) -> None:
    import numpy as np
    from ..trainer.checkpoints import Checkpointer
    ck = Checkpointer(directory)
    try:
        state, _meta = ck.restore_to_host(step=step)
        import jax
        leaves = jax.tree_util.tree_flatten_with_path(state)[0]
        report.n_leaves = len(leaves)
        for path, leaf in leaves:
            arr = np.asarray(leaf)
            if arr.dtype.kind == "f" and not np.isfinite(arr).all():
                report.nonfinite_leaves.append(jax.tree_util.keystr(path))
    except Exception as e:  # noqa: BLE001 — any failure is the finding
        report.errors.append(f"deep restore failed: {type(e).__name__}: {e}")
    finally:
        ck.close()


def verify_checkpoint(directory: str, step: Optional[int] = None,
                      deep: bool = False,
                      all_steps: bool = False) -> List[StepReport]:
    """Check `step` (default: latest), or every step with `all_steps`.

    Returns reports sorted by step. An empty directory yields a single
    failing pseudo-report (step=-1) rather than raising, so the CLI can
    exit 1 uniformly.
    """
    steps: List[int]
    if step is not None:
        steps = [step]
    else:
        try:
            entries = [int(e) for e in os.listdir(directory)
                       if e.isdigit()
                       and os.path.isdir(os.path.join(directory, e))]
        except OSError as e:
            return [StepReport(step=-1, ok=False,
                               errors=[f"cannot list {directory}: {e}"])]
        entries.sort()
        if not entries:
            return [StepReport(step=-1, ok=False,
                               errors=[f"no step dirs under {directory}"])]
        steps = entries if all_steps else [entries[-1]]
    return [verify_step(directory, s, deep=deep) for s in steps]


def corrupt_step_dir(directory: str, step: int,
                     mode: str = "garbage") -> int:
    """Deliberately corrupt a step dir (chaos-test helper).

    mode="garbage"  overwrite every file under `<step>/state` with junk
                    (observed to make orbax restore raise while the step
                    stays listed — the worst case for naive restore).
    mode="truncate" zero out every file (caught by the shallow checker).
    Returns the number of files damaged.
    """
    state_dir = os.path.join(_step_dir(directory, step), "state")
    n = 0
    for dirpath, _, files in os.walk(state_dir):
        for f in files:
            p = os.path.join(dirpath, f)
            with open(p, "wb") as fh:
                if mode == "garbage":
                    fh.write(b"CORRUPTED-BY-CHAOS-TEST")
            n += 1
    if n == 0:
        raise FileNotFoundError(f"nothing to corrupt under {state_dir}")
    return n

"""Autoencoders for latent diffusion.

Capability parity with reference flaxdiff/models/autoencoder/
(autoencoder.py:11-160 AutoEncoder ABC with video flattening;
diffusers.py:14-153 StableDiffusionVAE wrapper; simple_autoenc.py stub).
Differences by design:

- The KL VAE here is FIRST-PARTY Flax (encoder/decoder resnet stacks,
  reparameterized sampling, scaling factor) rather than a wrapper over the
  diffusers pipeline — the reference's `SimpleAutoEncoder` was an
  unimplemented placeholder returning zeros (simple_autoenc.py:25-57); this
  one trains.
- `StableDiffusionVAE` remains available but is gated on the optional
  `diffusers` dependency (not installed in this environment).
"""
from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Any, Dict, Optional, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..typing import Dtype, PyTree
from .common import Downsample, ResidualBlock, Upsample


class AutoEncoder(ABC):
    """Interface every latent-diffusion codec implements.

    `encode`/`decode` auto-flatten video tensors [B, T, H, W, C] to frame
    batches and restore the temporal axis (reference autoencoder.py:48-117).
    """

    @abstractmethod
    def __encode__(self, x: jax.Array, key: Optional[jax.Array] = None,
                   **kwargs) -> jax.Array:
        ...

    @abstractmethod
    def __decode__(self, z: jax.Array, key: Optional[jax.Array] = None,
                   **kwargs) -> jax.Array:
        ...

    def _flat_apply(self, fn, x, **kwargs):
        if x.ndim == 5:
            b, t = x.shape[:2]
            out = fn(x.reshape(-1, *x.shape[2:]), **kwargs)
            return out.reshape(b, t, *out.shape[1:])
        return fn(x, **kwargs)

    def encode(self, x: jax.Array, key: Optional[jax.Array] = None,
               **kwargs) -> jax.Array:
        return self._flat_apply(self.__encode__, x, key=key, **kwargs)

    def decode(self, z: jax.Array, key: Optional[jax.Array] = None,
               **kwargs) -> jax.Array:
        return self._flat_apply(self.__decode__, z, key=key, **kwargs)

    def __call__(self, x: jax.Array, key: Optional[jax.Array] = None,
                 **kwargs) -> jax.Array:
        if key is not None:
            ekey, dkey = jax.random.split(key)
        else:
            ekey = dkey = None
        return self.decode(self.encode(x, key=ekey, **kwargs), key=dkey, **kwargs)

    @property
    @abstractmethod
    def downscale_factor(self) -> int:
        ...

    @property
    @abstractmethod
    def latent_channels(self) -> int:
        ...

    @property
    @abstractmethod
    def name(self) -> str:
        ...

    @abstractmethod
    def serialize(self) -> Dict[str, Any]:
        ...


class IdentityAutoEncoder(AutoEncoder):
    """Pixel-space no-op codec (downscale 1) so pixel and latent diffusion
    share one trainer code path."""

    def __init__(self, channels: int = 3):
        self._channels = channels

    def __encode__(self, x, key=None, **kwargs):
        return x

    def __decode__(self, z, key=None, **kwargs):
        return z

    @property
    def downscale_factor(self) -> int:
        return 1

    @property
    def latent_channels(self) -> int:
        return self._channels

    @property
    def name(self) -> str:
        return "identity"

    def serialize(self) -> Dict[str, Any]:
        return {"channels": self._channels}


# ---------------------------------------------------------------------------
# First-party KL VAE
# ---------------------------------------------------------------------------

def _res_block(features: int, norm_groups: int, dtype, name: str):
    """Shared resblock (temb=None path) — routes through the fused Pallas
    GroupNorm+SiLU kernel like the rest of the model zoo."""
    return ResidualBlock(features=features, norm_groups=norm_groups,
                         dtype=dtype, name=name)


class KLEncoder(nn.Module):
    """Image -> (mean, logvar) of the latent Gaussian."""

    latent_channels: int = 4
    block_channels: Sequence[int] = (64, 128, 256)
    layers_per_block: int = 2
    norm_groups: int = 8
    dtype: Optional[Dtype] = None

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        h = nn.Conv(self.block_channels[0], (3, 3), padding="SAME",
                    dtype=self.dtype, name="conv_in")(x)
        for i, ch in enumerate(self.block_channels):
            for j in range(self.layers_per_block):
                h = _res_block(ch, self.norm_groups, self.dtype,
                               name=f"down_{i}_{j}")(h)
            if i < len(self.block_channels) - 1:
                h = Downsample(ch, dtype=self.dtype,
                               name=f"downsample_{i}")(h)
        h = _res_block(self.block_channels[-1], self.norm_groups,
                       self.dtype, name="mid")(h)
        h = nn.GroupNorm(num_groups=self.norm_groups, dtype=jnp.float32,
                         name="norm_out")(h)
        h = nn.Conv(2 * self.latent_channels, (3, 3), padding="SAME",
                    dtype=jnp.float32, name="conv_out")(jax.nn.silu(h))
        # 1x1 quant conv as in the SD VAE head (reference diffusers.py:53-60)
        return nn.Conv(2 * self.latent_channels, (1, 1), dtype=jnp.float32,
                       name="quant_conv")(h)


class KLDecoder(nn.Module):
    """Latent -> image."""

    out_channels: int = 3
    block_channels: Sequence[int] = (64, 128, 256)   # same order as encoder
    layers_per_block: int = 2
    norm_groups: int = 8
    dtype: Optional[Dtype] = None

    @nn.compact
    def __call__(self, z: jax.Array) -> jax.Array:
        chans = list(self.block_channels)[::-1]
        h = nn.Conv(chans[0], (1, 1), dtype=self.dtype,
                    name="post_quant_conv")(z)
        h = nn.Conv(chans[0], (3, 3), padding="SAME", dtype=self.dtype,
                    name="conv_in")(h)
        h = _res_block(chans[0], self.norm_groups, self.dtype, name="mid")(h)
        for i, ch in enumerate(chans):
            for j in range(self.layers_per_block):
                h = _res_block(ch, self.norm_groups, self.dtype,
                               name=f"up_{i}_{j}")(h)
            if i < len(chans) - 1:
                h = Upsample(chans[i + 1], dtype=self.dtype,
                             name=f"upsample_{i}")(h)
        h = nn.GroupNorm(num_groups=self.norm_groups, dtype=jnp.float32,
                         name="norm_out")(h)
        return nn.Conv(self.out_channels, (3, 3), padding="SAME",
                       dtype=jnp.float32, name="conv_out")(jax.nn.silu(h))


def gaussian_sample(moments: jax.Array, key: Optional[jax.Array]
                    ) -> jax.Array:
    """Reparameterized sample (or mean if key is None) from concatenated
    (mean, logvar) — reference diffusers.py:75-84."""
    mean, logvar = jnp.split(moments, 2, axis=-1)
    if key is None:
        return mean
    logvar = jnp.clip(logvar, -30.0, 20.0)
    std = jnp.exp(0.5 * logvar)
    return mean + std * jax.random.normal(key, mean.shape, dtype=mean.dtype)


def kl_divergence(moments: jax.Array) -> jax.Array:
    """KL(q || N(0,1)) per batch element, for VAE training."""
    mean, logvar = jnp.split(moments, 2, axis=-1)
    logvar = jnp.clip(logvar, -30.0, 20.0)
    return 0.5 * jnp.sum(mean ** 2 + jnp.exp(logvar) - 1.0 - logvar,
                         axis=tuple(range(1, mean.ndim)))


class JittedVAE(AutoEncoder):
    """Shared plumbing for params-bound codecs (KL VAE, SD VAE): jitted
    encode/decode with the scaling factor as a jit ARGUMENT, not a
    captured constant — users set it after measuring latent std (SD
    convention) and a baked-in trace would silently keep using the old
    value. Subclasses call `_bind(moments_fn, decode_fn)` after setting
    `params`/`scaling_factor` and provide only the architecture-specific
    moment/decode bodies."""

    def _bind(self, moments_fn, decode_fn) -> None:
        # moments_fn(params, x) -> concatenated (mean, logvar);
        # decode_fn(params, z) -> image, z already unscaled
        def _enc(params, x, key, scale):
            return gaussian_sample(moments_fn(params, x), key) * scale

        def _dec(params, z, scale):
            return decode_fn(params, z / scale)

        self._moments_fn = jax.jit(moments_fn)
        self._enc = jax.jit(_enc)
        self._enc_mean = jax.jit(lambda p, x, s: _enc(p, x, None, s))
        self._dec = jax.jit(_dec)

    def moments(self, x: jax.Array) -> jax.Array:
        """Raw (mean, logvar) — used by VAE training losses."""
        return self._moments_fn(self.params, x)

    def __encode__(self, x, key=None, **kwargs):
        scale = jnp.float32(self.scaling_factor)
        if key is None:
            return self._enc_mean(self.params, x, scale)
        return self._enc(self.params, x, key, scale)

    def __decode__(self, z, key=None, **kwargs):
        return self._dec(self.params, z, jnp.float32(self.scaling_factor))

    @property
    def downscale_factor(self) -> int:
        return self._downscale

    @property
    def latent_channels(self) -> int:
        return self._latent_channels


class KLAutoEncoder(JittedVAE):
    """First-party trainable KL VAE bound to a parameter tree.

    Construct with `KLAutoEncoder.create(key, ...)` for fresh params or pass
    existing params. The jitted per-frame encode/decode mirror the
    reference's SD wrapper surface (diffusers.py:72-96).
    """

    def __init__(self, params: PyTree, *, latent_channels: int = 4,
                 out_channels: int = 3,
                 block_channels: Sequence[int] = (64, 128, 256),
                 layers_per_block: int = 2, norm_groups: int = 8,
                 scaling_factor: float = 1.0,
                 dtype: Optional[Dtype] = None):
        self.params = params
        self._latent_channels = latent_channels
        self._out_channels = out_channels
        self._block_channels = tuple(block_channels)
        self._layers_per_block = layers_per_block
        self._norm_groups = norm_groups
        self.scaling_factor = scaling_factor
        self.encoder = KLEncoder(latent_channels, self._block_channels,
                                 layers_per_block, norm_groups, dtype)
        self.decoder = KLDecoder(out_channels, self._block_channels,
                                 layers_per_block, norm_groups, dtype)
        self._downscale = 2 ** (len(self._block_channels) - 1)
        self._bind(
            lambda params, x: self.encoder.apply(
                {"params": params["encoder"]}, x),
            lambda params, z: self.decoder.apply(
                {"params": params["decoder"]}, z))

    @classmethod
    def create(cls, key: jax.Array, *, input_channels: int = 3,
               image_size: int = 64, **kwargs) -> "KLAutoEncoder":
        ek, dk = jax.random.split(key)
        latent_channels = kwargs.get("latent_channels", 4)
        block_channels = tuple(kwargs.get("block_channels", (64, 128, 256)))
        layers = kwargs.get("layers_per_block", 2)
        groups = kwargs.get("norm_groups", 8)
        dtype = kwargs.get("dtype", None)
        enc = KLEncoder(latent_channels, block_channels, layers, groups, dtype)
        dec = KLDecoder(kwargs.get("out_channels", input_channels),
                        block_channels, layers, groups, dtype)
        down = 2 ** (len(block_channels) - 1)
        x = jnp.zeros((1, image_size, image_size, input_channels))
        z = jnp.zeros((1, image_size // down, image_size // down,
                       latent_channels))
        params = {"encoder": enc.init(ek, x)["params"],
                  "decoder": dec.init(dk, z)["params"]}
        kwargs.setdefault("out_channels", input_channels)
        return cls(params, **kwargs)

    @property
    def name(self) -> str:
        return "kl_vae"

    def serialize(self) -> Dict[str, Any]:
        return {
            "latent_channels": self._latent_channels,
            "out_channels": self._out_channels,
            "block_channels": list(self._block_channels),
            "layers_per_block": self._layers_per_block,
            "norm_groups": self._norm_groups,
            "scaling_factor": self.scaling_factor,
        }


class StableDiffusionVAE(AutoEncoder):
    """Wrapper over the pretrained SD VAE via the optional `diffusers`
    package (reference diffusers.py:14-153). Raises a clear ImportError when
    diffusers is not installed."""

    def __init__(self, modelname: str = "CompVis/stable-diffusion-v1-4",
                 revision: str = "bf16", dtype: Dtype = jnp.bfloat16):
        try:
            from diffusers import FlaxAutoencoderKL
            from diffusers.models.vae_flax import FlaxDecoder, FlaxEncoder
        except ImportError as e:
            raise ImportError(
                "StableDiffusionVAE requires the optional `diffusers` "
                "package; install it or use KLAutoEncoder (first-party)."
            ) from e
        vae, params = FlaxAutoencoderKL.from_pretrained(
            modelname, revision=revision, dtype=dtype)
        self.modelname, self.revision, self.dtype = modelname, revision, dtype
        self._vae, self._params = vae, params
        self.scaling_factor = vae.config.scaling_factor

        # Call the NHWC FlaxEncoder/FlaxDecoder submodules directly: the
        # top-level FlaxAutoencoderKL.encode/decode take NCHW at the public
        # boundary, which would layout-mangle this NHWC pipeline (reference
        # diffusers.py:30-96 uses the same submodule approach).
        enc_mod = FlaxEncoder(
            in_channels=vae.config.in_channels,
            out_channels=vae.config.latent_channels,
            down_block_types=vae.config.down_block_types,
            block_out_channels=vae.config.block_out_channels,
            layers_per_block=vae.config.layers_per_block,
            act_fn=vae.config.act_fn,
            norm_num_groups=vae.config.norm_num_groups,
            double_z=True, dtype=dtype)
        dec_mod = FlaxDecoder(
            in_channels=vae.config.latent_channels,
            out_channels=vae.config.out_channels,
            up_block_types=vae.config.up_block_types,
            block_out_channels=vae.config.block_out_channels,
            layers_per_block=vae.config.layers_per_block,
            act_fn=vae.config.act_fn,
            norm_num_groups=vae.config.norm_num_groups,
            dtype=dtype)
        quant = nn.Conv(2 * vae.config.latent_channels, (1, 1),
                        padding="VALID", dtype=dtype)
        post_quant = nn.Conv(vae.config.latent_channels, (1, 1),
                             padding="VALID", dtype=dtype)

        def _enc(x, key):
            h = enc_mod.apply({"params": params["encoder"]}, x,
                              deterministic=True)
            moments = quant.apply({"params": params["quant_conv"]}, h)
            return gaussian_sample(moments, key) * self.scaling_factor

        def _dec(z):
            z = post_quant.apply({"params": params["post_quant_conv"]},
                                 z / self.scaling_factor)
            return dec_mod.apply({"params": params["decoder"]}, z,
                                 deterministic=True)

        self._enc = jax.jit(_enc, static_argnums=())
        self._dec = jax.jit(_dec)
        # Both are statically known from the config — no probe forward needed.
        self._downscale = 2 ** (len(vae.config.block_out_channels) - 1)
        self._latent_channels = vae.config.latent_channels

    def __encode__(self, x, key=None, **kwargs):
        return self._enc(x, key)

    def __decode__(self, z, key=None, **kwargs):
        return self._dec(z)

    @property
    def downscale_factor(self) -> int:
        return self._downscale

    @property
    def latent_channels(self) -> int:
        return self._latent_channels

    @property
    def name(self) -> str:
        return "stable_diffusion"

    def serialize(self) -> Dict[str, Any]:
        return {"modelname": self.modelname, "revision": self.revision,
                "dtype": str(self.dtype)}


def _sd_vae(**kwargs):
    # local import: sd_vae imports this module for the ABC
    from .sd_vae import SDVAE
    return SDVAE(**kwargs) if "params" in kwargs else SDVAE.create(
        jax.random.PRNGKey(kwargs.pop("seed", 0)), **kwargs)


AUTOENCODER_REGISTRY = {
    "identity": IdentityAutoEncoder,
    "kl_vae": KLAutoEncoder,
    "sd_vae": _sd_vae,
    "stable_diffusion": StableDiffusionVAE,
}

"""Span-based tracing in Chrome trace-event JSON (Perfetto-loadable).

`jax.profiler` traces answer "what did the DEVICE do" at kernel
granularity; the question this module answers is one level up: "what
did the RUN do" — fit phases, checkpoint save/restore/commit rounds,
sampler loops, recovery paths — as host-side spans cheap enough to
leave on for a whole job. The output is the Chrome trace-event format
(`{"traceEvents": [...]}`), so `chrome://tracing` / https://ui.perfetto.dev
render the run's life directly, and `scripts/analyze_trace.py`-style
tooling can post-process it.

Bounded memory: events accumulate in a capped in-memory list; past
`max_events` new spans are counted in `dropped` instead of stored (a
run that traces too finely degrades its trace, never its training).
`save()` rewrites the whole file atomically and may be called
repeatedly (the trainer flushes at the end of fit; crash loses at most
the spans since the last flush).
"""
from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Dict, Optional


class TraceRecorder:
    """Collects spans/instants; writes Chrome trace-event JSON.

    `on_drop` (optional, `callable(n)`) is invoked OUTSIDE the recorder
    lock each time events are dropped past the bound — the telemetry
    hub wires it to the `telemetry/trace_dropped_events` counter so a
    trace that silently degraded is visible in the metrics stream, not
    only in the saved file's `flaxdiff_dropped_events` field.
    """

    def __init__(self, path: str, pid: int = 0,
                 max_events: int = 100_000, clock=time.perf_counter,
                 on_drop=None):
        self.path = path
        self.pid = int(pid)
        self.max_events = max_events
        self._clock = clock
        self._t0 = clock()
        self._lock = threading.Lock()
        self._events: list = [
            {"ph": "M", "name": "process_name", "pid": self.pid,
             "args": {"name": f"host {self.pid}"}}]
        self.dropped = 0
        self._on_drop = on_drop

    @property
    def has_on_drop(self) -> bool:
        return self._on_drop is not None

    def set_on_drop(self, fn) -> None:
        """Late-wire the drop callback: a recorder handed to a
        `Telemetry` hub bare (not via `Telemetry.create`) gets the
        `telemetry/trace_dropped_events` counter attached here, so
        front-door and scheduler lanes share one accounting path."""
        self._on_drop = fn

    def _now_us(self) -> float:
        return (self._clock() - self._t0) * 1e6

    def _emit(self, ev: Dict[str, object]) -> None:
        dropped = False
        with self._lock:
            if len(self._events) >= self.max_events:
                self.dropped += 1
                dropped = True
            else:
                self._events.append(ev)
        if dropped and self._on_drop is not None:
            self._on_drop(1)

    @contextlib.contextmanager
    def span(self, name: str, cat: str = "run",
             args: Optional[Dict[str, object]] = None):
        """Complete-event ("X") span around a block. Exceptions
        propagate; the span still closes (marked `error: true`) so a
        crash is visible in the timeline at the exact span it died in."""
        ts = self._now_us()
        err = False
        try:
            yield
        except BaseException:
            err = True
            raise
        finally:
            ev: Dict[str, object] = {
                "ph": "X", "name": name, "cat": cat, "pid": self.pid,
                "tid": threading.get_ident() % 1_000_000,
                "ts": ts, "dur": self._now_us() - ts}
            a = dict(args or {})
            if err:
                a["error"] = True
            if a:
                ev["args"] = a
            self._emit(ev)

    def event_at(self, name: str, start_s: float, end_s: float,
                 cat: str = "run",
                 args: Optional[Dict[str, object]] = None,
                 tid: Optional[int] = None) -> None:
        """Complete event from EXPLICIT timestamps already taken on this
        recorder's clock (`time.perf_counter` by default). The serving
        request tracer records host timestamps inline in the dispatch
        and completion threads (zero device syncs) and emits the spans
        after the fact — this is the emission path."""
        ev: Dict[str, object] = {
            "ph": "X", "name": name, "cat": cat, "pid": self.pid,
            "tid": (int(tid) if tid is not None
                    else threading.get_ident() % 1_000_000),
            "ts": (start_s - self._t0) * 1e6,
            "dur": max(0.0, end_s - start_s) * 1e6}
        if args:
            ev["args"] = args
        self._emit(ev)

    def instant_at(self, name: str, at_s: float, cat: str = "event",
                   args: Optional[Dict[str, object]] = None,
                   tid: Optional[int] = None) -> None:
        """Instant event at an explicit recorder-clock timestamp."""
        ev: Dict[str, object] = {
            "ph": "i", "s": "p", "name": name, "cat": cat,
            "pid": self.pid,
            "tid": (int(tid) if tid is not None
                    else threading.get_ident() % 1_000_000),
            "ts": (at_s - self._t0) * 1e6}
        if args:
            ev["args"] = args
        self._emit(ev)

    def instant(self, name: str, cat: str = "event",
                args: Optional[Dict[str, object]] = None) -> None:
        ev: Dict[str, object] = {
            "ph": "i", "s": "p", "name": name, "cat": cat,
            "pid": self.pid, "tid": threading.get_ident() % 1_000_000,
            "ts": self._now_us()}
        if args:
            ev["args"] = args
        self._emit(ev)

    def save(self) -> str:
        """Atomic rewrite of the full trace file; safe to call often."""
        with self._lock:
            events = list(self._events)
            dropped = self.dropped
        doc = {"traceEvents": events, "displayTimeUnit": "ms"}
        if dropped:
            doc["flaxdiff_dropped_events"] = dropped
        os.makedirs(os.path.dirname(os.path.abspath(self.path)) or ".",
                    exist_ok=True)
        tmp = self.path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f)
        os.replace(tmp, self.path)
        return self.path

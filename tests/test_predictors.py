"""Forward/backward identity tests for prediction transforms."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flaxdiff_tpu.predictors import (
    DirectPredictionTransform,
    EpsilonPredictionTransform,
    KarrasPredictionTransform,
    VPredictionTransform,
    get_transform,
)
from flaxdiff_tpu.schedulers import (
    CosineNoiseSchedule,
    KarrasVENoiseSchedule,
    LinearNoiseSchedule,
)

VP_TRANSFORMS = [EpsilonPredictionTransform, DirectPredictionTransform,
                 VPredictionTransform]


@pytest.mark.parametrize("tcls", VP_TRANSFORMS)
@pytest.mark.parametrize("scls", [LinearNoiseSchedule, CosineNoiseSchedule])
def test_forward_backward_identity_vp(tcls, scls):
    """If the net predicted the exact target, to_x0_eps must recover (x0, eps)."""
    s = scls(timesteps=100)
    tr = tcls()
    key = jax.random.PRNGKey(0)
    x0 = jax.random.normal(key, (4, 8, 8, 3))
    noise = jax.random.normal(jax.random.fold_in(key, 1), (4, 8, 8, 3))
    t = jnp.asarray([5, 25, 60, 90])
    x_t, target = tr.forward(s, x0, noise, t)
    pred = tr.transform_output(x_t, t, target, s)
    x0_hat, eps_hat = tr.to_x0_eps(x_t, t, pred, s)
    np.testing.assert_allclose(x0_hat, x0, rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(eps_hat, noise, rtol=1e-3, atol=1e-3)


def test_forward_backward_identity_karras():
    s = KarrasVENoiseSchedule(timesteps=100)
    tr = KarrasPredictionTransform(sigma_data=0.5)
    key = jax.random.PRNGKey(0)
    x0 = jax.random.normal(key, (4, 8, 8, 3))
    noise = jax.random.normal(jax.random.fold_in(key, 1), (4, 8, 8, 3))
    t = jnp.asarray([5.0, 25.0, 60.0, 90.0])
    x_t, target = tr.forward(s, x0, noise, t)
    np.testing.assert_allclose(target, x0)  # EDM target is x0
    # The exact raw net output F such that D = x0:
    sigma, c_skip, c_out, c_in = tr._coeffs(s, t)
    from flaxdiff_tpu.schedulers.common import bcast_right
    raw = (x0 - bcast_right(c_skip, 4) * x_t) / bcast_right(c_out, 4)
    pred = tr.transform_output(x_t, t, raw, s)
    x0_hat, eps_hat = tr.to_x0_eps(x_t, t, pred, s)
    np.testing.assert_allclose(x0_hat, x0, rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(eps_hat, noise, rtol=1e-2, atol=1e-2)


def test_karras_input_scale_matches_edm():
    s = KarrasVENoiseSchedule(timesteps=100)
    tr = KarrasPredictionTransform(sigma_data=0.5)
    t = jnp.asarray([10.0, 50.0])
    sigma = s.sigmas(t)
    c_in = tr.input_scale(s, t)
    np.testing.assert_allclose(c_in, 1.0 / jnp.sqrt(sigma**2 + 0.25), rtol=1e-5)


def test_v_prediction_definition():
    s = CosineNoiseSchedule(timesteps=100)
    tr = VPredictionTransform()
    key = jax.random.PRNGKey(2)
    x0 = jax.random.normal(key, (2, 4, 4, 1))
    noise = jax.random.normal(jax.random.fold_in(key, 1), (2, 4, 4, 1))
    t = jnp.asarray([10, 70])
    _, v = tr.forward(s, x0, noise, t)
    signal, sigma = s.rates(t)
    expected = (signal.reshape(-1, 1, 1, 1) * noise
                - sigma.reshape(-1, 1, 1, 1) * x0)
    np.testing.assert_allclose(v, expected, rtol=1e-5)


def test_registry():
    for name in ["epsilon", "x0", "v", "karras"]:
        assert get_transform(name) is not None

"""Core utilities: explicit PRNG threading, image transforms, tree helpers.

Capability parity with reference flaxdiff/utils.py (RandomMarkovState at
utils.py:93-98, clip/denormalize at 100-148, global-array assembly at
150-171), redesigned: RNG is an explicit `RngSeq` pytree usable inside jit,
and multi-host array assembly uses `jax.make_array_from_process_local_data`
instead of manual per-device splitting.
"""
from __future__ import annotations

from typing import Tuple

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np

from .typing import PRNGKey, PyTree


@flax.struct.dataclass
class RngSeq:
    """Functional RNG carrier — a pytree, safe to close over or carry in scan.

    Equivalent in capability to the reference's RandomMarkovState
    (flaxdiff/utils.py:93-98) but jit-native: `next_key` returns
    (new_state, key) without host round-trips.
    """

    key: PRNGKey

    @classmethod
    def create(cls, seed_or_key) -> "RngSeq":
        if isinstance(seed_or_key, int):
            return cls(key=jax.random.PRNGKey(seed_or_key))
        return cls(key=seed_or_key)

    def next_key(self) -> Tuple["RngSeq", PRNGKey]:
        new_key, sub = jax.random.split(self.key)
        return RngSeq(key=new_key), sub

    def next_keys(self, n: int) -> Tuple["RngSeq", PRNGKey]:
        keys = jax.random.split(self.key, n + 1)
        return RngSeq(key=keys[0]), keys[1:]

    def fold_in(self, data) -> "RngSeq":
        return RngSeq(key=jax.random.fold_in(self.key, data))


# Back-compat alias for code written against the reference naming.
RandomMarkovState = RngSeq


def apply_jax_platforms_env() -> None:
    """Honor JAX_PLATFORMS even when a site hook imported jax at
    interpreter startup with another platform latched (the env var alone
    is then too late — observed on this build VM's tunneled-TPU image).
    Call before the first device access. Shared by train.py, bench
    stages, and tests/conftest.py."""
    import os
    p = os.environ.get("JAX_PLATFORMS")
    if p:
        jax.config.update("jax_platforms", p)


def normalize_images(x: jax.Array) -> jax.Array:
    """uint8 [0,255] -> float [-1,1] (reference: general_diffusion_trainer.py:258)."""
    return (x.astype(jnp.float32) - 127.5) / 127.5


def denormalize_images(x: jax.Array) -> jax.Array:
    """float [-1,1] -> uint8 [0,255] (reference: utils.py:100-148)."""
    return jnp.clip(x * 127.5 + 127.5, 0, 255).astype(jnp.uint8)


def clip_images(x: jax.Array, clip_min: float = -1.0, clip_max: float = 1.0) -> jax.Array:
    return jnp.clip(x, clip_min, clip_max)


def to_unit_float(images) -> "np.ndarray":
    """uint8 / [-1,1] / [0,1] / [0,255]-float images -> float32 [0, 1]
    (host-side numpy).

    One place for the range heuristic shared by metrics (FID feature
    input) and logging (grid PNGs), so the two can never disagree about a
    batch's range. Float ranges are detected by value: min < -0.01 means
    [-1,1]; max > 1.5 means [0,255] (un-normalized decode output); else
    already [0,1]."""
    import numpy as np
    images = np.asarray(images)
    if images.dtype == np.uint8:
        return images.astype(np.float32) / 255.0
    images = images.astype(np.float32)
    if images.min() < -0.01:       # [-1,1] convention
        images = (images + 1.0) / 2.0
    elif images.max() > 1.5:       # float [0,255] convention
        images = images / 255.0
    return np.clip(images, 0.0, 1.0)


def cfg_uncond_splice(emb: jax.Array, uncond: jax.Array,
                      uncond_mask: jax.Array) -> jax.Array:
    """CFG-dropout splice: where uncond_mask[b] is True, replace sample b's
    conditioning with the (broadcast) null embedding via jnp.where — the
    reference's correct masking semantics (inputs/__init__.py:122-137).

    Single source of truth for both the train step and input-config paths.
    """
    if uncond_mask.shape[0] != emb.shape[0]:
        raise ValueError(
            f"uncond_mask batch {uncond_mask.shape[0]} != "
            f"embedding batch {emb.shape[0]}")
    mask = uncond_mask.reshape((emb.shape[0],) + (1,) * (emb.ndim - 1))
    uncond_b = jnp.broadcast_to(uncond.astype(emb.dtype), emb.shape)
    return jnp.where(mask, uncond_b, emb)


def count_params(tree: PyTree) -> int:
    return sum(int(np.prod(x.shape)) for x in jax.tree_util.tree_leaves(tree)
               if hasattr(x, "shape"))


def tree_bytes(tree: PyTree) -> int:
    return sum(x.size * x.dtype.itemsize for x in jax.tree_util.tree_leaves(tree)
               if hasattr(x, "dtype"))


def fill_params_by_path(template: PyTree, flat: dict, prefix: str = "",
                        label: str = "weight load") -> PyTree:
    """Fill `template`'s leaves from a '/'-path-keyed dict of arrays
    (optionally under `prefix`), matched by PATH with shape checking:
    every template leaf must be present and every prefixed key consumed,
    or a ValueError lists what's missing/mismatched/unused. Template
    leaves only need .shape/.dtype, so `jax.eval_shape` output works —
    no real init required. Shared by the InceptionV3 FID loader and the
    SD-VAE torch-weight loader."""
    sub = {k[len(prefix):]: v for k, v in flat.items()
           if k.startswith(prefix)}
    leaves_kp, treedef = jax.tree_util.tree_flatten_with_path(template)
    missing, mismatched, leaves = [], [], []
    for path, leaf in leaves_kp:
        key = "/".join(
            getattr(p, "key", getattr(p, "name", str(p))) for p in path)
        if key not in sub:
            missing.append(key)
            leaves.append(leaf)
            continue
        arr = sub.pop(key)
        if tuple(arr.shape) != tuple(leaf.shape):
            mismatched.append(f"{key}: file {arr.shape} vs model "
                              f"{tuple(leaf.shape)}")
            leaves.append(leaf)
            continue
        leaves.append(jnp.asarray(arr, dtype=leaf.dtype))
    errors = []
    if missing:
        errors.append(f"missing: {sorted(missing)[:5]}"
                      f"{' ...' if len(missing) > 5 else ''} "
                      f"({len(missing)} total)")
    if mismatched:
        errors.append(f"shape mismatches: {mismatched[:5]}")
    if sub:
        errors.append(f"unused keys: {sorted(sub)[:5]} ({len(sub)} total)")
    if errors:
        raise ValueError(
            f"{label} failed{f' under {prefix!r}' if prefix else ''} — "
            + "; ".join(errors))
    return jax.tree_util.tree_unflatten(treedef, leaves)


def form_global_array(path, array: np.ndarray, global_mesh: jax.sharding.Mesh,
                      axis_name: str = "data") -> jax.Array:
    """Assemble a host-local numpy batch shard into a global jax.Array.

    TPU-native replacement for the reference's manual per-device split +
    `make_array_from_single_device_arrays` (flaxdiff/utils.py:150-171,
    trainer/simple_trainer.py:43-65).
    """
    sharding = jax.sharding.NamedSharding(
        global_mesh, jax.sharding.PartitionSpec(axis_name))
    return jax.make_array_from_process_local_data(sharding, array)


def convert_to_global_tree(global_mesh: jax.sharding.Mesh, pytree: PyTree,
                           axis_name: str = "data") -> PyTree:
    return jax.tree_util.tree_map_with_path(
        lambda p, x: form_global_array(p, x, global_mesh, axis_name), pytree)


def serialize_model_config(name: str, config: dict) -> dict:
    """Flatten a model config for experiment tracking (reference utils.py:59-84)."""
    out = {"model_name": name}
    for k, v in config.items():
        if callable(v) and hasattr(v, "__name__"):
            out[k] = v.__name__
        elif isinstance(v, (list, tuple)):
            out[k] = list(v)
        else:
            out[k] = str(v) if not isinstance(v, (int, float, bool, str, dict, type(None))) else v
    return out

#!/usr/bin/env python
"""Visualize space-filling-curve patch serialization orders.

The visual counterpart of the SFC machinery in
flaxdiff_tpu/models/sfc.py (reference demo_hilbert_curve.py and the
matplotlib demos in reference models/hilbert.py:373-714): draws the
raster, zigzag, and Hilbert traversal orders over a patch grid, checks
the patchify/unpatchify round trip to machine precision, and plots the
token-distance locality profile that motivates Hilbert ordering for
1-D sequence models (S5/SSM blocks) over 2-D images.

Usage:
  python scripts/demo_sfc.py --grid 16 --out sfc_demo.png
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def _force_cpu():
    """This demo is pure index math + plotting — never wait on an
    accelerator. A site hook may have latched a tunneled-TPU platform at
    interpreter startup, ignoring JAX_PLATFORMS (tests/conftest.py
    rationale); the config update wins while backends are uninitialized."""
    import jax
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception as e:  # noqa: BLE001 — degrade, but visibly
        print(f"note: could not pin the cpu platform "
              f"({type(e).__name__}: {e}); the demo may wait on an "
              f"accelerator backend", file=sys.stderr)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--grid", type=int, default=16,
                    help="patch grid side (any size; non-powers of two "
                         "exercise the overscan+filter construction)")
    ap.add_argument("--out", default="sfc_demo.png")
    args = ap.parse_args(argv)

    _force_cpu()
    import matplotlib
    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    from flaxdiff_tpu.models.sfc import (hilbert_indices,
                                         inverse_permutation,
                                         sfc_patchify, sfc_unpatchify,
                                         zigzag_indices)

    g = args.grid
    orders = {
        "raster": np.arange(g * g),
        "zigzag": zigzag_indices(g, g),
        "hilbert": hilbert_indices(g, g),
    }

    fig, axes = plt.subplots(2, 3, figsize=(13, 8.5))
    for ax, (name, idx) in zip(axes[0], orders.items()):
        ys, xs = np.divmod(idx, g)
        ax.plot(xs + 0.5, ys + 0.5, lw=1.1, color="tab:blue")
        ax.scatter([xs[0] + 0.5], [ys[0] + 0.5], color="tab:green",
                   zorder=3, label="start")
        ax.scatter([xs[-1] + 0.5], [ys[-1] + 0.5], color="tab:red",
                   zorder=3, label="end")
        ax.set_xlim(0, g)
        ax.set_ylim(g, 0)
        ax.set_aspect("equal")
        ax.set_title(f"{name} ({g}x{g} patches)")
        ax.legend(loc="lower right", fontsize=8)

    # locality profile: mean 2-D distance between tokens k sequence
    # steps apart — the quantity SFC ordering improves for 1-D scans
    ks = np.unique(np.round(np.logspace(0, np.log10(g * g / 2),
                                        24)).astype(int))
    ax = axes[1][0]
    for name, idx in orders.items():
        ys, xs = np.divmod(idx, g)
        pts = np.stack([xs, ys], 1).astype(float)
        mean_d = [np.mean(np.linalg.norm(pts[k:] - pts[:-k], axis=1))
                  for k in ks]
        ax.plot(ks, mean_d, marker="o", ms=3, label=name)
    ax.set_xscale("log")
    ax.set_xlabel("sequence distance k")
    ax.set_ylabel("mean 2-D patch distance")
    ax.set_title("locality: 2-D distance at sequence distance k")
    ax.legend()

    # round trip on a real image through the jit-compatible path
    rng = np.random.default_rng(0)
    img = rng.normal(size=(1, g * 4, g * 4, 3)).astype(np.float32)
    ax = axes[1][1]
    maes = {}
    for name in ("hilbert", "zigzag"):
        idx = orders[name]
        tokens, inv = sfc_patchify(img, patch_size=4, indices=idx)
        back = sfc_unpatchify(tokens, inv, patch_size=4,
                              h=g * 4, w=g * 4, channels=3)
        maes[name] = float(np.abs(np.asarray(back) - img).mean())
    ax.bar(list(maes), list(maes.values()), color="tab:blue")
    ax.set_title("patchify/unpatchify round-trip MAE (must be ~0)")
    ax.ticklabel_format(axis="y", style="sci", scilimits=(0, 0))

    # what a serialized image looks like: token index as intensity
    ax = axes[1][2]
    rank = inverse_permutation(orders["hilbert"]).reshape(g, g)
    im = ax.imshow(rank, cmap="viridis")
    ax.set_title("hilbert sequence position per patch")
    fig.colorbar(im, ax=ax, shrink=0.8)

    fig.tight_layout()
    fig.savefig(args.out, dpi=110)
    print(f"wrote {args.out}; round-trip MAE: " +
          ", ".join(f"{k}={v:.2e}" for k, v in maes.items()))
    assert all(v < 1e-7 for v in maes.values()), maes
    return 0


if __name__ == "__main__":
    sys.exit(main())

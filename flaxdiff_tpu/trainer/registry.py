"""Model registry: run records, per-metric best tracking, checkpoint
aliases.

Capability parity with the reference's wandb registry pipeline
(reference trainer/general_diffusion_trainer.py:560-727: push_to_registry
uploads the checkpoint as an artifact, then compares against the
sweep/project's historical best runs direction-aware and re-aliases
"best") — built on the local filesystem as the load-bearing store
(registry.json) with a wandb artifact push layered on when available, so
air-gapped training still gets registry semantics.
"""
from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, Optional


class ModelRegistry:
    """JSON-file registry of training runs and their best checkpoints.

    Layout of registry.json:
      {"runs": {run_name: {config, checkpoint_dir, step, metrics,
                           updated}},
       "best": {metric_name: {"run": ..., "value": ...,
                              "higher_is_better": ...}}}
    """

    def __init__(self, path: str):
        self.path = path
        self._data: Dict[str, Any] = {"runs": {}, "best": {}}
        if os.path.exists(path):
            with open(path) as fh:
                self._data = json.load(fh)
        self._data.setdefault("runs", {})
        self._data.setdefault("best", {})

    def _save(self):
        os.makedirs(os.path.dirname(os.path.abspath(self.path)) or ".",
                    exist_ok=True)
        # pid-unique tmp: concurrent writers (two runs finishing at once)
        # cannot clobber each other's tmp file; last replace wins whole
        tmp = f"{self.path}.tmp.{os.getpid()}"
        with open(tmp, "w") as fh:
            json.dump(self._data, fh, indent=2, sort_keys=True)
        os.replace(tmp, self.path)

    # -- write ---------------------------------------------------------------
    def register_run(self, name: str, checkpoint_dir: str, step: int,
                     metrics: Dict[str, float],
                     metric_directions: Optional[Dict[str, bool]] = None,
                     config: Optional[Dict[str, Any]] = None
                     ) -> Dict[str, bool]:
        """Record/update a run; returns {metric: became_best} — the
        reference's is-this-the-best-run comparison
        (general_diffusion_trainer.py:596-703), direction-aware via
        `metric_directions` ({name: higher_is_better}, default lower)."""
        directions = metric_directions or {}
        run = self._data["runs"].setdefault(name, {})
        run.update({
            "checkpoint_dir": checkpoint_dir,
            "step": int(step),
            "metrics": {k: float(v) for k, v in metrics.items()},
            "updated": time.time(),
        })
        if config is not None:
            run["config"] = config

        became_best: Dict[str, bool] = {}
        for metric, value in metrics.items():
            hib = bool(directions.get(metric, False))
            cur = self._data["best"].get(metric)
            better = (cur is None
                      or (value > cur["value"] if hib
                          else value < cur["value"]))
            became_best[metric] = bool(better)
            if better:
                self._data["best"][metric] = {
                    "run": name, "value": float(value),
                    "higher_is_better": hib,
                    "checkpoint_dir": checkpoint_dir, "step": int(step),
                }
        self._save()
        return became_best

    def push_artifact(self, name: str, checkpoint_dir: str,
                      project: Optional[str] = None) -> bool:
        """Upload the checkpoint directory as a wandb artifact when wandb
        is importable and a run is active (reference
        general_diffusion_trainer.py:560-594); returns False offline."""
        try:
            import wandb
            if wandb.run is None:
                return False
            art = wandb.Artifact(name.replace("/", "_"), type="model")
            art.add_dir(checkpoint_dir)
            wandb.run.log_artifact(art, aliases=["latest"])
            return True
        except Exception:
            return False

    # -- read ----------------------------------------------------------------
    def runs(self) -> Dict[str, Any]:
        return dict(self._data["runs"])

    def best_run(self, metric: str) -> Optional[Dict[str, Any]]:
        return self._data["best"].get(metric)

    def best_checkpoint(self, metric: str) -> Optional[str]:
        best = self.best_run(metric)
        return best["checkpoint_dir"] if best else None

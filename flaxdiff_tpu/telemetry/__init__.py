"""Telemetry subsystem: step-phase timing, goodput/badput accounting,
cross-host metric aggregation, and trace-span export.

The reference logs wall-clock epoch time only (SURVEY §5.1); after the
resilience PRs this framework *survives* faults but could not *account*
for them. This package is the observability layer every perf item on
the ROADMAP depends on — you cannot speed up what you cannot attribute:

  metrics     bounded-memory registry (counters / gauges / fixed-bucket
              streaming histograms) with pluggable exporters: JSONL
              (default system of record), Prometheus textfile (atomic
              rename, textfile-collector convention), and fan-out into
              the existing trainer loggers (JsonlLogger / wandb)
  phases      StepPhaseTimer: every training step decomposed into
              data_wait / host / device / checkpoint / eval / other,
              with the device phase closed by `block_until_ready` so
              async dispatch cannot lie; feeds profiling.MFUMeter
  goodput     GoodputLedger: ALL wall-clock classified productive vs.
              badput (compile, checkpoint_commit, restart, data_stall,
              coordination_lost, ...), persisted in goodput.json so the
              account accumulates across job incarnations
  aggregate   CrossHostAggregator: min/max/mean/p50/p99/spread of
              per-host metrics over the resilience Transport (real pods
              via jax.distributed; CPU tests via InMemoryTransport)
  tracing     TraceRecorder: host-side spans (fit phases, checkpoint
              rounds, sampler loops, recovery paths) as Chrome
              trace-event JSON, loadable in Perfetto
  reqtrace    RequestTracer: request-scoped serving traces — follow
              one SampleRequest through admission, queue, every
              micro-batch round (program key, bucket, step codes),
              and completion; spans + request_trace JSONL rows with
              zero added host syncs (counting-mock enforced). Trace
              ids PROPAGATE across hops: the front door mints one and
              the replica scheduler adopts it (`begin(parent=...)`),
              so one Chrome lane shows door + replica + rounds
  slo         SloEngine: online per-tenant SLO attainment and
              multi-window error-budget burn rates from the same
              timestamps the door already takes — the primary input
              to burn-rate brownout and SLO-weighted routing
  flightrec   FlightRecorder: bounded in-memory rings of recent trace
              rows / resilience events / metric snapshots; a declared
              incident (replica death, engine rebuild, pool
              exhaustion, quarantine spike, elastic transition,
              quorum eviction) dumps one correlated
              incident-<id>.json bundle for offline diagnosis
  programs    ProgramRegistry: per-compiled-program evidence rows in
              programs.jsonl (cache key, compile ms, jaxpr FLOPs,
              cost_analysis flops/bytes, HBM peak, hardware
              fingerprint) — per-program roofline attribution and the
              measured substrate scripts/compare_runs.py diffs
  numerics    training-health: in-graph NumericsConfig/numerics_aux
              (per-module grad/param norms, update ratios, non-finite
              counts inside the jitted step at a cadence) + host-side
              AnomalyDetector (EMA z-score, hard non-finite/floor
              triggers, warn|skip_step|rollback actions) + NaN
              provenance helpers that name the module that blew up
  memory      MemoryMonitor: HBM gauges from device.memory_stats()
              (bytes-in-use, peak, per-step watermark, utilization),
              falling back to host-RSS gauges (/proc/self/statm) on
              backends without allocator stats
  devprof     DeviceProfiler + trace attribution parser: automated
              jax.profiler windows (step/round cadence, trigger file)
              parsed into byte-stable devprof.jsonl rows — device ms
              by op family and model module, collective-vs-compute
              split, layout-copy/fusion-gap counters — reconciled
              against the program registry (measured MFU, roofline
              verdict, predicted-vs-measured comm calibration)
  hub         Telemetry: the bundle the other layers talk to, plus the
              process-global default (`global_telemetry`) for layers
              with no plumbing

Offline analysis: `python scripts/diagnose_run.py <telemetry_dir>`
renders the goodput / phase / pod-skew report from the JSONL stream.
See docs/OBSERVABILITY.md for metric names and the badput taxonomy.

Dependency direction: trainer/, data/, and inference/ import telemetry;
telemetry imports nothing from them (and from resilience only lazily,
to classify a failed aggregation round).
"""
from .aggregate import (
    DISABLED_SENTINEL,
    AggregationDisabled,
    CrossHostAggregator,
)
from .devprof import (
    DEVPROF_FILENAME,
    DeviceProfiler,
    read_devprof,
    reconcile,
    summarize_events,
)
from .goodput import GOODPUT_FILENAME, GoodputLedger
from .hub import (
    TELEMETRY_JSONL,
    TRACE_FILENAME,
    Telemetry,
    global_telemetry,
    set_global_telemetry,
    use_telemetry,
)
from .memory import MemoryMonitor
from .metrics import (
    DEFAULT_BUCKET_BOUNDS,
    Counter,
    Gauge,
    Histogram,
    JsonlExporter,
    LoggerExporter,
    MetricsRegistry,
    PrometheusTextfileExporter,
)
from .numerics import (
    ANOMALY_ACTIONS,
    Anomaly,
    AnomalyConfig,
    AnomalyDetector,
    NumericsConfig,
    flatten_aux,
    nonfinite_modules,
    numerics_aux,
    probe_aux,
    top_level_modules,
    tree_l2_norm,
    tree_nonfinite_count,
    unwrap_module_tree,
)
from .phases import PHASES, StepPhaseTimer
from .programs import (
    PROGRAMS_FILENAME,
    ProgramRegistry,
    hardware_fingerprint,
    read_registry,
    register_on_first_call,
    stable_json,
)
from .flightrec import (
    BUNDLE_SCHEMA_VERSION,
    INCIDENT_PREFIX,
    FlightRecorder,
    list_incidents,
)
from .reqtrace import RequestTrace, RequestTracer
from .slo import SloConfig, SloEngine
from .tracing import TraceRecorder

__all__ = [
    "Telemetry",
    "global_telemetry",
    "set_global_telemetry",
    "use_telemetry",
    "MetricsRegistry",
    "Counter",
    "Gauge",
    "Histogram",
    "DEFAULT_BUCKET_BOUNDS",
    "JsonlExporter",
    "PrometheusTextfileExporter",
    "LoggerExporter",
    "StepPhaseTimer",
    "PHASES",
    "GoodputLedger",
    "GOODPUT_FILENAME",
    "CrossHostAggregator",
    "AggregationDisabled",
    "DISABLED_SENTINEL",
    "TraceRecorder",
    "TELEMETRY_JSONL",
    "TRACE_FILENAME",
    "NumericsConfig",
    "numerics_aux",
    "probe_aux",
    "flatten_aux",
    "nonfinite_modules",
    "top_level_modules",
    "tree_l2_norm",
    "tree_nonfinite_count",
    "unwrap_module_tree",
    "AnomalyConfig",
    "AnomalyDetector",
    "Anomaly",
    "ANOMALY_ACTIONS",
    "MemoryMonitor",
    "DeviceProfiler",
    "DEVPROF_FILENAME",
    "read_devprof",
    "reconcile",
    "summarize_events",
    "ProgramRegistry",
    "PROGRAMS_FILENAME",
    "hardware_fingerprint",
    "read_registry",
    "register_on_first_call",
    "stable_json",
    "RequestTrace",
    "RequestTracer",
    "SloConfig",
    "SloEngine",
    "FlightRecorder",
    "INCIDENT_PREFIX",
    "BUNDLE_SCHEMA_VERSION",
    "list_incidents",
]

#!/usr/bin/env python
"""Training CLI for flaxdiff_tpu.

Capability parity with reference training.py:83-680 (dataset selection,
architecture registry with +hilbert/+zigzag/+2d suffixes, warmup-cosine LR
with grad clip and adam/adamw/lamb, EMA / CFG-dropout knobs, dtype policy,
checkpointing, validation sampling) — reworked for this framework: mesh
axes are explicit (data/fsdp/tensor/seq), checkpoints are sharded orbax,
logging is JSONL (+wandb when available), and the inference config is
saved next to the checkpoints for DiffusionInferencePipeline.
"""
from __future__ import annotations

import argparse
import json
import os


def parse_args(argv=None):
    p = argparse.ArgumentParser(description="flaxdiff_tpu trainer")
    # data
    p.add_argument("--dataset", default="synthetic",
                   help="name in DATASET_REGISTRY")
    p.add_argument("--dataset_path", default=None)
    p.add_argument("--hf_text_key", default="text",
                   help="caption column for online:<hf-dataset> streaming")
    p.add_argument("--image_size", type=int, default=64)
    p.add_argument("--num_frames", type=int, default=0,
                   help=">0 trains a video model on [B,F,H,W,C] clips")
    p.add_argument("--audio_encoder", default="none",
                   choices=["none", "mel"],
                   help="condition video models on clip audio")
    p.add_argument("--batch_size", type=int, default=64)
    p.add_argument("--grain_workers", type=int, default=0)
    # grain throughput knobs (reference training.py:84-99 defaults at
    # corpus scale: 32 workers / 140 read threads / buffers 96/100)
    p.add_argument("--grain_worker_buffer", type=int, default=1)
    p.add_argument("--grain_read_threads", type=int, default=None)
    p.add_argument("--grain_read_buffer", type=int, default=None)
    # model
    p.add_argument("--architecture", default="unet",
                   help="registry name, e.g. unet, simple_dit+hilbert")
    p.add_argument("--model_config", default="{}",
                   help="JSON kwargs for the model constructor")
    p.add_argument("--autoencoder", default=None,
                   choices=["identity", "kl_vae", "sd_vae",
                            "stable_diffusion"],
                   help="latent-diffusion codec: the prior trains in the "
                        "codec's latent space and validation decodes "
                        "(reference training.py:192-195,339-345)")
    p.add_argument("--autoencoder_opts", default="{}",
                   help='JSON codec opts. sd_vae: {"npz": "sd_vae.npz"} '
                        "loads converted pretrained weights "
                        "(scripts/convert_sd_vae_weights.py); kl_vae/"
                        "sd_vae without weights init randomly (smoke "
                        "runs); stable_diffusion passes through to the "
                        "diffusers wrapper")
    p.add_argument("--dtype", default="bfloat16")
    # diffusion
    p.add_argument("--schedule", default="cosine")
    p.add_argument("--timesteps", type=int, default=1000)
    p.add_argument("--predictor", default="epsilon")
    # conditioning
    p.add_argument("--text_encoder", default="hash",
                   choices=["none", "hash", "clip"])
    p.add_argument("--uncond_prob", type=float, default=0.12)
    # optimization (reference defaults: training.py:185-189, 213)
    p.add_argument("--optimizer", default="adamw",
                   choices=["adam", "adamw", "lamb"])
    p.add_argument("--lr", type=float, default=2.7e-4)
    p.add_argument("--warmup_steps", type=int, default=10000)
    p.add_argument("--total_steps", type=int, default=100000)
    p.add_argument("--grad_clip", type=float, default=1.0)
    p.add_argument("--flat_optimizer", action="store_true",
                   help="run the optimizer over one raveled vector per "
                        "dtype (fused updates; elementwise optimizers "
                        "only — not lamb)")
    p.add_argument("--flat_params", action="store_true",
                   help="params/EMA/opt-state live as one padded vector "
                        "per dtype: fused optimizer+EMA+apply updates "
                        "AND flat grads via AD (supersedes "
                        "--flat_optimizer; elementwise optimizers only; "
                        "changes checkpoint layout)")
    p.add_argument("--attn_bhld", action="store_true",
                   help="project attention q/k/v straight into the "
                        "flash kernel's [B,H,L,D] layout (no per-op "
                        "transposes). Sets FLAXDIFF_ATTN_BHLD for the "
                        "whole process, so in multi-host runs every "
                        "host resolves the same layout from the same "
                        "command line (an env var set by hand on only "
                        "some hosts would compile divergent programs)")
    p.add_argument("--grad_accum", type=int, default=1,
                   help=">1 accumulates gradients over k micro-batches "
                        "per optimizer update (optax.MultiSteps)")
    p.add_argument("--ema_decay", type=float, default=0.999)
    # parallelism
    p.add_argument("--mesh_data", type=int, default=-1)
    p.add_argument("--mesh_fsdp", type=int, default=1)
    p.add_argument("--mesh_seq", type=int, default=1)
    p.add_argument("--mesh_tensor", type=int, default=1,
                   help=">1 enables Megatron tensor parallelism over the "
                        "tensor mesh axis (head-sharded attention)")
    # checkpoint / logging / validation
    p.add_argument("--checkpoint_dir", default="./checkpoints/run")
    p.add_argument("--save_every", type=int, default=1000)
    p.add_argument("--log_every", type=int, default=100)
    p.add_argument("--profile_dir", default=None,
                   help="capture a jax.profiler trace of a few post-warmup "
                        "steps into this directory")
    p.add_argument("--telemetry_dir", default=None,
                   help="enable the telemetry subsystem "
                        "(docs/OBSERVABILITY.md): per-step phase timings "
                        "+ pod-aggregated metrics into telemetry.jsonl, "
                        "a cumulative goodput/badput account in "
                        "goodput.json, host-side spans in a "
                        "Perfetto-loadable trace.json, and the program "
                        "evidence registry in programs.jsonl (per "
                        "compiled program: cache key, compile ms, "
                        "FLOPs, hardware fingerprint). Costs one device "
                        "sync per SAMPLED step (exact device-phase "
                        "timing; --telemetry_sample_every thins it). "
                        "Analyze with scripts/diagnose_run.py; diff two "
                        "runs with scripts/compare_runs.py")
    p.add_argument("--telemetry_sample_every", type=int, default=1,
                   help="with --telemetry_dir, close async dispatch for "
                        "exact device-phase timing only every N-th step "
                        "— off-sample steps add zero host syncs and "
                        "phase/goodput attribution moves to window "
                        "granularity (docs/OBSERVABILITY.md 'Sampled "
                        "phase timing'). 1 = per-step exact timing")
    p.add_argument("--pipeline_depth", type=int, default=2,
                   help="bounded-depth asynchronous dispatch: the fit "
                        "loop keeps up to N steps in flight so the "
                        "device pipeline stays full across step "
                        "boundaries; 0 disables the bound (the "
                        "log-cadence loss fetch is then the only "
                        "settle point)")
    p.add_argument("--no_nonfinite_gate", action="store_true",
                   help="disable the in-graph non-finite gate (an "
                        "elementwise select that keeps the previous "
                        "value wherever an update is non-finite, so "
                        "the live state is finite by construction); "
                        "disabling restores the legacy synchronous "
                        "save-cadence loss check")
    p.add_argument("--gate_counter", action="store_true",
                   help="carry an in-graph [3] int32 counter of the "
                        "elements the non-finite gate masked in "
                        "params/opt-state/EMA, surfaced once per log "
                        "window as numerics/gate_activations* counters "
                        "+ a gate_activated event. Opt-in: the count "
                        "reduces over every state leaf (slower XLA "
                        "compile) and adds a checkpoint pytree leaf — "
                        "flip per run, not mid-run. Requires the gate "
                        "(incompatible with --no_nonfinite_gate)")
    p.add_argument("--flash_tune_cache", default=None,
                   help="per-shape flash-attention autotuner cache dir "
                        "(ops/autotune.py): before the first step, a "
                        "shape-scouting eval_shape pass + measured "
                        "probes pick block sizes and the native-d "
                        "choice per attention shape and persist them "
                        "here; a warm cache re-measures nothing. "
                        "FLAXDIFF_FLASH_BLOCK_Q/K / _NATIVE_D env "
                        "overrides always win over cached plans")
    p.add_argument("--loss_ring", type=int, default=0,
                   help="device-resident in-graph loss ring of this "
                        "many slots: the jitted step records each "
                        "step's loss on device and the fit loop "
                        "fetches the whole window with ONE readback "
                        "per ring, so even log_every=1 costs one sync "
                        "per window (per-step losses arrive "
                        "retroactively as window_losses). 0 disables; "
                        "changes the checkpointed state tree by one "
                        "[N] leaf, so pick per run")
    p.add_argument("--compilation_cache_dir", default=None,
                   help="persistent XLA compilation cache directory: "
                        "relaunches (and coordinated restarts) reload "
                        "compiled programs instead of paying the jit "
                        "compile again — the fit loop detects the warm "
                        "first step and attributes it productive "
                        "instead of compile badput")
    p.add_argument("--prometheus_textfile", default=None,
                   help="also export the telemetry snapshot to this path "
                        "in Prometheus text format (atomic rename; "
                        "node-exporter textfile-collector convention). "
                        "Requires --telemetry_dir")
    p.add_argument("--numerics_cadence", type=int, default=0,
                   help="every N steps run the training-health monitor "
                        "inside the jitted step (per-module grad/param "
                        "norms, update ratios, non-finite counts; "
                        "docs/OBSERVABILITY.md). Off-cadence steps run "
                        "the unmonitored program unchanged; 0 disables")
    p.add_argument("--anomaly_action", default="warn",
                   choices=["warn", "skip_step", "rollback"],
                   help="what a detected numerics anomaly does: warn "
                        "(events/metrics only), skip_step (non-finite "
                        "updates gated in-graph, never applied), or "
                        "rollback (restore best state / newest "
                        "restorable checkpoint on hard anomalies)")
    p.add_argument("--watchdog_timeout", type=float, default=None,
                   help="seconds without a completed step before the "
                        "train-loop watchdog checkpoints and exits "
                        "cleanly (docs/RESILIENCE.md); default off. Size "
                        "it at several multiples of the step time.")
    p.add_argument("--coordinated_restart", default="auto",
                   choices=["auto", "on", "off"],
                   help="pod-consistent checkpointing: two-phase "
                        "ledger commits + consensus restore + crash "
                        "barriers (docs/RESILIENCE.md). auto = on "
                        "whenever jax.process_count() > 1")
    p.add_argument("--commit_barrier_timeout", type=float, default=600.0,
                   help="seconds survivors wait at a commit/restore "
                        "barrier before declaring a peer dead and "
                        "taking the checkpoint-and-exit path")
    p.add_argument("--elastic", default="off", choices=["on", "off"],
                   help="elastic world (docs/RESILIENCE.md): survivors "
                        "of a lost host SHRINK the world and keep "
                        "training instead of exiting on "
                        "coordination_lost; replacement hosts are "
                        "re-admitted live at commit boundaries; hard "
                        "numerics anomalies become pod quorum votes. "
                        "Implies coordinated checkpointing.")
    p.add_argument("--elastic_shrink_window", type=float, default=5.0,
                   help="seconds survivors wait for each peer's "
                        "presence answer in a shrink round before "
                        "declaring it dead")
    p.add_argument("--elastic_min_world", type=int, default=1,
                   help="refuse to shrink below this many hosts "
                        "(checkpoint-and-exit instead)")
    p.add_argument("--elastic_restart_cost", type=float, default=0.0,
                   help="estimated relaunch overhead (scheduler queue, "
                        "container pull) in seconds — feeds only the "
                        "badput-reclaimed estimate of elastic "
                        "transitions")
    p.add_argument("--val_every", type=int, default=0,
                   help="0 disables in-loop validation")
    p.add_argument("--val_samples", type=int, default=8)
    p.add_argument("--val_steps", type=int, default=200)
    p.add_argument("--val_guidance", type=float, default=3.0)
    p.add_argument("--val_metrics", default="",
                   help="comma list of {fid, clip, clip_score}")
    p.add_argument("--inception_weights", default=None,
                   help=".npz from scripts/convert_inception_weights.py "
                        "(standard FID; random features otherwise)")
    p.add_argument("--sampler", default="euler_ancestral")
    p.add_argument("--wandb_project", default=None)
    p.add_argument("--wandb_resume", default=None, metavar="RUN_ID",
                   help="resume this wandb run id; its logged model "
                        "artifact is auto-downloaded when no local "
                        "checkpoint exists (reference "
                        "simple_trainer.py:194-211)")
    p.add_argument("--registry", default=None,
                   help="path to registry.json for cross-run best tracking "
                        "(default: <checkpoint_dir>/../registry.json)")
    p.add_argument("--run_name", default=None)
    p.add_argument("--seed", type=int, default=0)
    return p.parse_args(argv)


def configure_compilation_cache(cache_dir):
    """Enable JAX's persistent compilation cache rooted at `cache_dir`.

    Thresholds are zeroed so even small programs (the monitored twin,
    eval samplers) cache — a coordinated restart then pays ~no compile
    badput, and the trainer's warm-first-step reclassification keeps
    the goodput account honest about it. Returns True when the cache
    was configured (False on a jax too old to support it — the run
    proceeds uncached rather than dying)."""
    import jax
    try:
        jax.config.update("jax_compilation_cache_dir", str(cache_dir))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        try:
            jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                              -1)
        except AttributeError:
            pass        # knob added after the min_compile_time one
    except AttributeError:
        import warnings
        warnings.warn("this jax has no persistent compilation cache "
                      "config; --compilation_cache_dir ignored",
                      stacklevel=2)
        return False
    return True


def main(argv=None):
    args = parse_args(argv)

    import jax

    from flaxdiff_tpu.utils import apply_jax_platforms_env
    apply_jax_platforms_env()
    if args.compilation_cache_dir:
        configure_compilation_cache(args.compilation_cache_dir)
    if args.flash_tune_cache:
        from flaxdiff_tpu.ops import autotune as _flash_autotune
        _flash_autotune.activate(args.flash_tune_cache)
    import jax.numpy as jnp
    import numpy as np
    import optax

    from flaxdiff_tpu.data.dataloaders import get_dataset_grain
    from flaxdiff_tpu.data.dataset_map import get_dataset
    from flaxdiff_tpu.inference.pipeline import save_pipeline_config
    from flaxdiff_tpu.inference.registry import build_model
    from flaxdiff_tpu.inputs import (CLIPTextEncoder, ConditionalInputConfig,
                                     DiffusionInputConfig, HashTextEncoder)
    from flaxdiff_tpu.parallel import create_mesh
    from flaxdiff_tpu.predictors import get_transform
    from flaxdiff_tpu.samplers import SAMPLER_REGISTRY
    from flaxdiff_tpu.schedulers import get_schedule
    from flaxdiff_tpu.trainer import (Checkpointer, DiffusionTrainer,
                                      TrainerConfig, ValidationConfig,
                                      Validator, make_logger)

    if jax.process_count() > 1:
        jax.distributed.initialize()

    # mesh
    mesh = create_mesh(axes={"data": args.mesh_data, "fsdp": args.mesh_fsdp,
                             "seq": args.mesh_seq,
                             "tensor": args.mesh_tensor})
    print(f"mesh: {dict(zip(mesh.axis_names, mesh.devices.shape))}")

    # conditioning
    encoder = None
    if args.text_encoder == "hash":
        encoder = HashTextEncoder.create()
    elif args.text_encoder == "clip":
        encoder = CLIPTextEncoder.from_modelname()
    conditions = []
    if encoder is not None:
        conditions.append(ConditionalInputConfig(encoder=encoder))
    input_config = DiffusionInputConfig(
        sample_data_key="sample",
        sample_data_shape=(args.image_size, args.image_size, 3),
        conditions=conditions)

    # data: tokenizer-free loader; text encoded host-side per batch.
    # "online:<name>" streams through OnlineStreamingDataLoader — a
    # registry name stays hermetic (records from the in-memory source),
    # anything else is fetched as a HuggingFace dataset (reference
    # onlineDatasetMap, online_loader.py:899-921).
    if args.dataset.startswith("online:"):
        from flaxdiff_tpu.data.dataloaders import to_trainer_batch
        from flaxdiff_tpu.data.dataset_map import DATASET_REGISTRY
        from flaxdiff_tpu.data.online_loader import OnlineStreamingDataLoader
        name = args.dataset.split(":", 1)[1]
        if name in DATASET_REGISTRY:
            media = get_dataset(name, image_size=args.image_size,
                                **({"root": args.dataset_path}
                                   if args.dataset_path else {}))
            src = media.source.get_source()
            records = [src[i] for i in range(len(src))]
            online = OnlineStreamingDataLoader(
                records, batch_size=args.batch_size,
                image_size=args.image_size, seed=args.seed)
        else:
            online = OnlineStreamingDataLoader.from_hf_dataset(
                name, text_key=args.hf_text_key,
                batch_size=args.batch_size,
                image_size=args.image_size, seed=args.seed)

        def _online_train(seed=0):
            for b in online:
                yield to_trainer_batch(b)

        loaded = {"train": _online_train}
    else:
        ds_kwargs = {"root": args.dataset_path} if args.dataset_path else {}
        if args.num_frames:
            ds_kwargs["num_frames"] = args.num_frames
        dataset = get_dataset(args.dataset, image_size=args.image_size,
                              **ds_kwargs)
        loaded = get_dataset_grain(dataset, batch_size=args.batch_size,
                                   image_size=args.image_size,
                                   worker_count=args.grain_workers,
                                   worker_buffer_size=args.grain_worker_buffer,
                                   read_threads=args.grain_read_threads,
                                   read_buffer_size=args.grain_read_buffer,
                                   seed=args.seed)

    # latent-diffusion codec (reference training.py:339-345): the prior
    # below trains over its latents — the encode happens INSIDE the
    # jitted train step, decode inside the validation sampler
    autoencoder = None
    if args.autoencoder:
        ae_opts = json.loads(args.autoencoder_opts)
        if args.autoencoder == "sd_vae" and "npz" in ae_opts:
            from flaxdiff_tpu.models.sd_vae import SDVAE
            autoencoder = SDVAE.from_npz(ae_opts.pop("npz"), **ae_opts)
        else:
            from flaxdiff_tpu.models.autoencoder import AUTOENCODER_REGISTRY
            builder = AUTOENCODER_REGISTRY[args.autoencoder]
            if args.autoencoder == "kl_vae":
                autoencoder = builder.create(
                    jax.random.PRNGKey(ae_opts.pop("seed", 0)), **ae_opts)
            else:
                autoencoder = builder(**ae_opts)
        if args.image_size % autoencoder.downscale_factor:
            raise SystemExit(
                f"--image_size {args.image_size} is not divisible by the "
                f"{autoencoder.name} codec's downscale factor "
                f"{autoencoder.downscale_factor}; the encoder would "
                "produce ceil-sized latents that disagree with the "
                "prior's sample shape")
        print(f"latent diffusion via {autoencoder.name}: "
              f"{autoencoder.downscale_factor}x downscale, "
              f"{autoencoder.latent_channels} latent channels")

    sample_channels = (autoencoder.latent_channels if autoencoder else 3)
    sample_size = (args.image_size // autoencoder.downscale_factor
                   if autoencoder else args.image_size)

    # model
    if args.attn_bhld:
        os.environ["FLAXDIFF_ATTN_BHLD"] = "1"
    model_kwargs = json.loads(args.model_config)
    model_kwargs.setdefault("dtype", args.dtype)
    if autoencoder is not None:
        model_kwargs.setdefault("output_channels", sample_channels)
    model = build_model(args.architecture, **model_kwargs)

    schedule = get_schedule(args.schedule, timesteps=args.timesteps)
    transform = get_transform(args.predictor)

    # audio conditioning for video models (one token per frame)
    audio_enc = None
    if args.audio_encoder == "mel":
        from flaxdiff_tpu.inputs import MelAudioEncoder
        audio_enc = MelAudioEncoder.create()

    ctx_shape = None
    if encoder is not None:
        ctx_shape = tuple(conditions[0].get_unconditional()[0].shape)
    elif audio_enc is not None and args.num_frames:
        ctx_shape = (args.num_frames, audio_enc.features)

    if args.num_frames:
        x0 = jnp.zeros((2, args.num_frames, sample_size,
                        sample_size, sample_channels))
    else:
        x0 = jnp.zeros((2, sample_size, sample_size, sample_channels))
    t0 = jnp.zeros((2,))
    c0 = (jnp.zeros((2,) + ctx_shape) if ctx_shape else None)

    def apply_fn(params, x, t, cond):
        ctx = None
        if cond is not None:
            ctx = cond.get("text", cond.get("audio"))
        return model.apply(params, x, t, ctx)

    def init_fn(key):
        return model.init(key, x0, t0, c0)

    # optimizer (reference training.py:594-608). MultiSteps advances the
    # inner schedule once per k micro-batches, so with --grad_accum the
    # horizons are scaled by k to keep warmup/decay aligned with the
    # total_steps micro-steps the fit loop actually runs.
    accum = max(args.grad_accum, 1)
    warmup = max(args.warmup_steps // accum, 1)
    # optax requires decay_steps > warmup_steps; short runs (resumes,
    # smoke tests) may configure total <= warmup
    lr = optax.warmup_cosine_decay_schedule(
        0.0, args.lr, warmup, max(args.total_steps // accum, warmup + 1))
    opt = {"adam": optax.adam, "adamw": optax.adamw,
           "lamb": optax.lamb}[args.optimizer]
    tx = optax.chain(optax.clip_by_global_norm(args.grad_clip), opt(lr))
    if args.flat_params:
        # the whole state lives flat (TrainerConfig.flat_params) — the
        # inner optimizer already sees flat vectors, so flat_optimizer
        # wrapping would be a redundant second flatten
        elementwise_safe = {"adam", "adamw"}
        if args.optimizer not in elementwise_safe:
            raise SystemExit(
                f"--flat_params is elementwise-only "
                f"({sorted(elementwise_safe)}); {args.optimizer!r} mixes "
                "information across a leaf's shape, which changes "
                "meaning under concatenation")
        args.flat_optimizer = False
    if args.flat_optimizer:
        # whitelist, not blacklist: a future optimizer added to `opt`
        # (lamb's trust ratio, adafactor's factored moments) silently
        # computes the WRONG thing over a concatenated vector
        elementwise_safe = {"adam", "adamw"}
        if args.optimizer not in elementwise_safe:
            raise SystemExit(
                f"--flat_optimizer is elementwise-only "
                f"({sorted(elementwise_safe)}); {args.optimizer!r} mixes "
                "information across a leaf's shape, which changes "
                "meaning under concatenation")
        from flaxdiff_tpu.trainer.optim import flat_optimizer
        # fuses the optax transform's per-leaf kernels into one update
        # per dtype (part of the r3 trace's ~330-kernel / 10 ms budget;
        # EMA and apply_updates remain leaf-wise — see trainer/optim.py).
        # Changes the optimizer-state checkpoint layout, so pick per run.
        tx = flat_optimizer(tx)
    if accum > 1:
        # micro-batch accumulation: k steps of summed grads per optimizer
        # update — effective batch k * batch_size without the memory.
        # EMA/step bookkeeping stays per-micro-step (ema_decay applies at
        # micro cadence, as with any MultiSteps wrapping).
        tx = optax.MultiSteps(tx, every_k_schedule=accum)

    null_cond = {}
    if encoder is not None:
        null_cond["text"] = jnp.asarray(conditions[0].get_unconditional())
    if audio_enc is not None and args.num_frames:
        null_cond["audio"] = jnp.zeros(
            (1, args.num_frames, audio_enc.features))
    null_cond = null_cond or None

    # fp16 gets a loss-scaling policy (DynamicScale constructed by the
    # trainer); bf16/f32 compute needs none.
    policy = None
    if args.dtype == "float16":
        from flaxdiff_tpu.typing import Policy
        policy = Policy(compute_dtype=jnp.float16)

    # The one name shared by the resume-pull and end-of-run push+registry
    # record: the two sites must never drift or resume stops finding the
    # pushed artifact.
    run_name = args.run_name or os.path.basename(
        os.path.normpath(args.checkpoint_dir))

    # Logger before checkpointer: wandb-run resume must be live so the
    # model artifact can be pulled back BEFORE restore looks at disk.
    os.makedirs(args.checkpoint_dir, exist_ok=True)
    wandb_kwargs = ({"id": args.wandb_resume, "resume": "must"}
                    if args.wandb_resume else {})
    logger = make_logger(project=args.wandb_project,
                         jsonl_path=os.path.join(args.checkpoint_dir,
                                                 "train_log.jsonl"),
                         **wandb_kwargs)
    # stream resilience events (retries, fallback restores, watchdog
    # stalls, ...) into the run log as structured records, in addition
    # to the counter metrics fit merges at log cadence
    from flaxdiff_tpu.trainer import attach_resilience
    attach_resilience(logger)

    # Telemetry (docs/OBSERVABILITY.md): phase timings, goodput ledger,
    # trace spans, pod aggregation. Installed as the process-global hub
    # so layers without plumbing (the data loader's workers, the
    # checkpointer) land on the same account; the world-of-one in-memory
    # transport keeps single-host runs on the identical aggregation
    # code path.
    telemetry = None
    if args.telemetry_dir:
        from flaxdiff_tpu.resilience.coordination import (
            InMemoryTransport, JaxDistributedTransport)
        from flaxdiff_tpu.telemetry import Telemetry, set_global_telemetry
        tel_transport = (JaxDistributedTransport("flaxdiff.telemetry")
                         if jax.process_count() > 1
                         else InMemoryTransport.make_world(1)[0])
        telemetry = Telemetry.create(
            args.telemetry_dir, transport=tel_transport,
            prometheus_textfile=args.prometheus_textfile, logger=logger)
        set_global_telemetry(telemetry)
    elif args.prometheus_textfile:
        raise SystemExit("--prometheus_textfile requires --telemetry_dir")
    if args.wandb_resume:
        has_local = any(d.isdigit()
                        for d in os.listdir(args.checkpoint_dir))
        if not has_local:
            # Process 0 downloads into the shared checkpoint_dir; the
            # others wait at the barrier (concurrent downloads into one
            # directory can corrupt the orbax step layout).
            pulled = None
            if jax.process_index() == 0:
                from flaxdiff_tpu.trainer.registry import pull_artifact
                pulled = pull_artifact(run_name, args.checkpoint_dir)
                if pulled:
                    print(f"pulled wandb artifact {run_name} -> {pulled}")
            if jax.process_count() > 1:
                from jax.experimental import multihost_utils
                multihost_utils.sync_global_devices("wandb_artifact_pull")
            has_local = any(d.isdigit()
                            for d in os.listdir(args.checkpoint_dir))
            if not has_local:
                # --wandb_resume is an explicit promise of prior state;
                # silently restarting from step 0 would also re-alias
                # "latest" to a from-scratch checkpoint at the end of the
                # run, clobbering the only copy of the real progress.
                raise SystemExit(
                    f"--wandb_resume {args.wandb_resume}: no local "
                    f"checkpoint under {args.checkpoint_dir} and the "
                    f"model artifact {run_name!r} could not be pulled "
                    "(no active wandb run / artifact missing / download "
                    "failed)")

    # Coordinated restart (docs/RESILIENCE.md): every host must restore
    # the SAME committed step after a crash — saves two-phase-commit
    # into ledger.jsonl and restores run a consensus round. The
    # in-memory world-of-one transport keeps single-host runs on the
    # identical code path (ledger included) without jax.distributed.
    coordinator = None
    elastic_manager = None
    want_elastic = args.elastic == "on"
    if want_elastic or args.coordinated_restart == "on" or (
            args.coordinated_restart == "auto"
            and jax.process_count() > 1):
        from flaxdiff_tpu.resilience.coordination import (
            RestartCoordinator, agree_epoch, default_transport)
        coord_transport = default_transport()
        # epoch-tagged vote payloads: the goodput ledger's incarnation
        # count IS the job-incarnation number, so a stale voter from a
        # previous life aborts the round instead of corrupting it
        # (docs/RESILIENCE.md). goodput.json is written by process 0
        # only, so non-0 hosts (host-local --telemetry_dir, torn read)
        # may hold a different local count — broadcast rank 0's value so
        # every host tags with the SAME epoch; divergent tags would
        # abort every future round.
        agreed = agree_epoch(
            coord_transport,
            (telemetry.goodput.incarnation
             if telemetry is not None else 0),
            timeout=args.commit_barrier_timeout)
        vote_transport = coord_transport
        if want_elastic:
            # Elastic world (docs/RESILIENCE.md "Elastic world"): the
            # manager owns membership; the coordinator's rounds run
            # over a MemberTransport so commits keep working unchanged
            # across shrink/grow transitions (keys are epoch-scoped,
            # ranks member-relative). The manager's ledger/validity
            # inputs are bound to the checkpointer below.
            from flaxdiff_tpu.resilience.elastic import (
                ElasticConfig, ElasticWorldManager, MemberTransport)
            elastic_manager = ElasticWorldManager(
                coord_transport,
                config=ElasticConfig(
                    shrink_window=args.elastic_shrink_window,
                    vote_timeout=args.commit_barrier_timeout,
                    min_world=args.elastic_min_world,
                    restart_cost_estimate=args.elastic_restart_cost))
            vote_transport = MemberTransport(elastic_manager)
        coordinator = RestartCoordinator(
            vote_transport,
            barrier_timeout=args.commit_barrier_timeout,
            epoch=agreed)
        if telemetry is not None:
            # stamp every raw telemetry row with the pod-agreed epoch:
            # a stale same-incarnation driver's rows stay attributable
            telemetry.set_epoch(agreed)
    ckpt = Checkpointer(args.checkpoint_dir, coordinator=coordinator)
    if elastic_manager is not None:
        elastic_manager.ledger = ckpt.ledger
        elastic_manager.valid_steps = ckpt.locally_valid_steps
    trainer = DiffusionTrainer(
        apply_fn=apply_fn, init_fn=init_fn, tx=tx, schedule=schedule,
        transform=transform, mesh=mesh,
        config=TrainerConfig(ema_decay=args.ema_decay,
                             uncond_prob=args.uncond_prob,
                             log_every=args.log_every, seed=args.seed,
                             profile_dir=args.profile_dir,
                             flat_params=args.flat_params,
                             watchdog_timeout=args.watchdog_timeout,
                             numerics_cadence=args.numerics_cadence,
                             anomaly_action=args.anomaly_action,
                             pipeline_depth=args.pipeline_depth,
                             telemetry_sample_every=(
                                 args.telemetry_sample_every),
                             gate_nonfinite=not args.no_nonfinite_gate,
                             gate_counter=args.gate_counter,
                             loss_ring=args.loss_ring),
        policy=policy, null_cond=null_cond, checkpointer=ckpt,
        autoencoder=autoencoder, telemetry=telemetry,
        elastic=elastic_manager)

    if ckpt.latest_step() is not None:
        step = trainer.restore_checkpoint()
        print(f"resumed from step {step}")

    # persist the inference config next to the checkpoints
    save_pipeline_config(args.checkpoint_dir, {
        "model": {"name": args.architecture, **model_kwargs},
        "schedule": {"name": args.schedule, "timesteps": args.timesteps},
        "predictor": args.predictor,
        "input_config": (input_config.serialize() if conditions else None),
        # informational: inference must supply the codec object itself
        # (weights live outside the checkpoint), but the config records
        # which codec and shape the prior was trained against
        "autoencoder": ({"name": args.autoencoder,
                         **autoencoder.serialize()}
                        if autoencoder else None),
        "flat_params": args.flat_params,
    })
    # (flat-params runs: the trainer itself persists param_template.json
    # beside the checkpoints — see DiffusionTrainer._write_param_template)

    validator = None
    if args.val_every:
        val_metrics = []
        for name in filter(None, args.val_metrics.split(",")):
            if name == "fid":
                from flaxdiff_tpu.metrics import get_fid_metric
                val_metrics.append(get_fid_metric(
                    params_file=args.inception_weights))
            elif name == "clip":
                from flaxdiff_tpu.metrics import get_clip_metric
                val_metrics.append(get_clip_metric())
            elif name == "clip_score":
                from flaxdiff_tpu.metrics import get_clip_score_metric
                val_metrics.append(get_clip_score_metric())
            elif name == "psnr":
                from flaxdiff_tpu.metrics import get_psnr_metric
                val_metrics.append(get_psnr_metric())
            elif name == "ssim":
                from flaxdiff_tpu.metrics import get_ssim_metric
                val_metrics.append(get_ssim_metric())
            else:
                raise SystemExit(f"unknown --val_metrics entry {name!r}")
        validator = Validator(
            model_fn=apply_fn, schedule=schedule, transform=transform,
            sampler=SAMPLER_REGISTRY[args.sampler](),
            metrics=val_metrics, autoencoder=autoencoder,
            config=ValidationConfig(
                num_samples=args.val_samples,
                diffusion_steps=args.val_steps,
                guidance_scale=args.val_guidance if encoder else 0.0,
                resolution=args.image_size,
                sequence_length=args.num_frames or None))

    raw_iter = loaded["train"](seed=args.seed)

    def encode_text(batch):
        """Host-side conditioning encode: captions -> text embeddings,
        clip audio -> per-frame audio tokens. Raw strings stay in the
        batch (put_batch strips non-numerics before jit) so validation
        metrics that need prompts — CLIPScore — still see batch['text']."""
        if encoder is not None and isinstance(batch.get("text"), list):
            batch.setdefault("cond", {})["text"] = np.asarray(
                encoder(batch["text"]))
        if audio_enc is not None and isinstance(batch.get("audio"), dict):
            fw = batch["audio"].get("framewise_audio")
            if fw is not None:
                batch.setdefault("cond", {})["audio"] = np.asarray(
                    audio_enc(fw))
        # keep only what the step consumes — raw audio waveforms / mel /
        # mask side-channels would otherwise ride the H2D copy every step
        return {k: v for k, v in batch.items()
                if k in ("sample", "cond", "text")}

    # Background-thread text encoding, 2 batches ahead: encode cost hides
    # behind device compute (placement decision measured in
    # scripts/bench_text_encode.py; SURVEY §7.3(4)).
    from flaxdiff_tpu.data.prefetch import prefetch_map
    it = prefetch_map(encode_text, raw_iter, depth=2)

    # Elastic re-shard hook (docs/RESILIENCE.md "Shrink-to-survive"):
    # after a world change the trainer swaps in a pipeline rebuilt for
    # the surviving (rank, size) — the grain index sampler re-shards,
    # not just the online loader. Epoch-offset seed so the re-sharded
    # stream does not replay the pre-shrink order.
    data_factory = None
    if "reshard" in loaded:
        def data_factory(view):
            resharded = loaded["reshard"](view.rank, view.size)
            return prefetch_map(encode_text,
                                resharded(seed=args.seed + view.epoch),
                                depth=2)
    if args.flash_tune_cache:
        # shape-scouting + measured probes BEFORE the first compile, so
        # the train step picks the tuned per-shape plans up; the peeked
        # batch is chained back so no data is dropped
        import itertools as _it
        first = next(it)
        plans = trainer.autotune_flash(trainer.put_batch(first))
        if plans:
            print(f"flash autotuner probed {len(plans)} shape(s) -> "
                  f"{args.flash_tune_cache}")
        it = _it.chain([first], it)
    done = 0
    while done < args.total_steps:
        chunk = min(args.val_every or args.total_steps,
                    args.total_steps - done)
        hist = trainer.fit(
            it, total_steps=chunk, save_every=args.save_every,
            data_factory=data_factory,
            callbacks=[lambda s, l, m: logger.log(
                {"loss": l, **m}, step=done + s)])
        done += chunk
        if validator is not None and done < args.total_steps:
            cond = unc = None
            if encoder is not None:
                # conditioning must mirror the train-step cond pytree
                # ({"text": ...}) — apply_fn routes on the dict key
                prompts = ["a photo"] * args.val_samples
                cond = {"text": jnp.asarray(encoder(prompts))}
                unc = {"text": jnp.asarray(
                    input_config.get_unconditionals(args.val_samples)[0])}
            real_batch = next(it)  # real images for FID / CLIP references
            if telemetry is not None:
                import contextlib as _ctx
                eval_scope = _ctx.ExitStack()
                eval_scope.enter_context(
                    telemetry.span("validation", cat="eval",
                                   args={"step": done}))
                eval_scope.enter_context(
                    telemetry.goodput.measure_badput("eval"))
            else:
                eval_scope = None
            try:
                result = validator.run(trainer.get_params(use_ema=True),
                                       conditioning=cond, unconditional=unc,
                                       batch=real_batch)
            finally:
                if eval_scope is not None:
                    eval_scope.close()
            logger.log({f"val/{k}": v
                        for k, v in result["metrics"].items()}, step=done)
            logger.log_images("val/samples",
                              Validator.to_uint8(result["samples"]),
                              step=done)
    logger.log({"final_loss": hist["final_loss"]}, step=done)

    # The final save is ASYNC: it must be fully on disk before the
    # registry records it and push_artifact copies the directory — an
    # unfinalized step would upload a partial checkpoint.
    ckpt.wait_until_finished()

    # registry: record the run + per-metric best across runs; push a
    # wandb artifact when a run is live (reference
    # general_diffusion_trainer.py:560-727). Process 0 only — every host
    # sees the same final metrics and registry.json lives on a shared
    # filesystem.
    if jax.process_index() != 0:
        if telemetry is not None:
            telemetry.close()
        logger.finish()
        ckpt.wait_until_finished()
        return hist
    from flaxdiff_tpu.trainer import ModelRegistry
    reg_path = args.registry or os.path.join(
        os.path.dirname(os.path.abspath(args.checkpoint_dir)),
        "registry.json")
    registry = ModelRegistry(reg_path)
    final_metrics = {"loss": hist["final_loss"]}
    directions = {"loss": False}
    if validator is not None:
        for m in validator.metrics:
            if m.name in validator.tracker.best:
                final_metrics[m.name] = validator.tracker.best[m.name]
                directions[m.name] = m.higher_is_better
    became_best = registry.register_run(
        run_name, checkpoint_dir=args.checkpoint_dir, step=done,
        metrics=final_metrics, metric_directions=directions,
        config={"architecture": args.architecture,
                "schedule": args.schedule, "dataset": args.dataset})
    registry.push_artifact(run_name, args.checkpoint_dir)
    logger.log({f"registry/best_{k}": v for k, v in became_best.items()},
               step=done)

    if telemetry is not None:
        # final snapshot + trace/goodput flush; the goodput line is the
        # run's one-sentence efficiency summary
        telemetry.export(step=done)
        telemetry.close()
        t = telemetry.goodput.totals()
        if t["goodput_fraction"] is not None:
            print(f"goodput: {t['goodput_fraction']:.1%} of "
                  f"{t['total_s']:.0f}s attributed wall-clock "
                  f"(incarnation {t['incarnations']}); report: "
                  f"python scripts/diagnose_run.py {args.telemetry_dir}")
    logger.finish()
    ckpt.wait_until_finished()
    print(f"done: {done} steps, final loss {hist['final_loss']:.4f}")
    return hist


if __name__ == "__main__":
    main()

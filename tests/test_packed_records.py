"""Tests for the native packed-record reader (C++/ctypes) + writer."""
import numpy as np
import pytest

from flaxdiff_tpu.data.packed_records import (
    PackedRecordReader,
    PackedRecordSource,
    PackedRecordWriter,
    pack_record,
    unpack_record,
    write_image_dataset,
)


def test_pack_unpack_roundtrip():
    rec = {"image": b"\x00\x01\x02", "caption": "hello".encode(),
           "empty": b""}
    assert unpack_record(pack_record(rec)) == rec


def test_native_reader_roundtrip(tmp_path, rng):
    path = str(tmp_path / "data.fdtr")
    blobs = [bytes(rng.integers(0, 256, size=n, dtype=np.uint8))
             for n in (10, 0, 1024, 7)]
    with PackedRecordWriter(path) as w:
        for b in blobs:
            w.write({"payload": b})
    reader = PackedRecordReader(path)
    assert len(reader) == 4
    for i, b in enumerate(blobs):
        assert reader[i]["payload"] == b
    with pytest.raises(IndexError):
        reader.record_bytes(99)
    with pytest.raises(IndexError):
        reader.record_bytes(-1)
    reader.close()


def test_native_reader_rejects_garbage(tmp_path):
    path = str(tmp_path / "bad.fdtr")
    with open(path, "wb") as f:
        f.write(b"NOTAMAGICVALUE" + b"\x00" * 64)
    with pytest.raises(IOError):
        PackedRecordReader(path)


def test_native_reader_rejects_truncated_index(tmp_path):
    import struct
    path = str(tmp_path / "trunc.fdtr")
    with open(path, "wb") as f:
        f.write(b"FDTR" + struct.pack("<I", 1) + struct.pack("<Q", 1000))
    with pytest.raises(IOError):
        PackedRecordReader(path)


def test_packed_image_source_end_to_end(tmp_path, rng):
    path = str(tmp_path / "imgs.fdtr")
    images = rng.integers(0, 255, size=(6, 12, 12, 3)).astype(np.uint8)
    captions = [f"caption {i}" for i in range(6)]
    write_image_dataset(path, images, captions)

    src = PackedRecordSource(path).get_source()
    assert len(src) == 6
    rec = src[2]
    assert rec["text"] == "caption 2"
    # PNG is lossless: exact roundtrip
    np.testing.assert_array_equal(rec["image"], images[2])


def test_packed_source_in_grain_pipeline(tmp_path, rng):
    from flaxdiff_tpu.data import get_dataset_grain
    from flaxdiff_tpu.data.sources.base import MediaDataset
    from flaxdiff_tpu.data.sources.images import ImageAugmenter

    path = str(tmp_path / "imgs2.fdtr")
    images = rng.integers(0, 255, size=(16, 10, 10, 3)).astype(np.uint8)
    write_image_dataset(path, images, [f"c{i}" for i in range(16)])

    ds = MediaDataset(source=PackedRecordSource(path),
                      augmenter=ImageAugmenter(image_size=8))
    loaded = get_dataset_grain(ds, batch_size=4, image_size=8)
    batch = next(loaded["train"](seed=0))
    assert batch["sample"].shape == (4, 8, 8, 3)
    assert len(batch["text"]) == 4


def test_pack_dataset_script_roundtrip(tmp_path):
    """scripts/pack_dataset.py packs an image folder into shards the
    reader (incl. the native C++ path) can decode."""
    import subprocess
    import sys

    import cv2

    src = tmp_path / "imgs" / "roses"
    src.mkdir(parents=True)
    rng = np.random.default_rng(0)
    for i in range(6):
        img = rng.integers(0, 255, (32, 40, 3), np.uint8)
        cv2.imwrite(str(src / f"{i}.png"), img)
    out = tmp_path / "shards"
    res = subprocess.run(
        [sys.executable, "scripts/pack_dataset.py", "--src",
         str(tmp_path / "imgs"), "--out", str(out), "--shards", "2",
         "--image_size", "16", "--caption_from_dirname"],
        capture_output=True, text=True, cwd=".")
    assert res.returncode == 0, res.stderr
    import json
    meta = json.loads(res.stdout.strip().splitlines()[-1])
    assert meta["total"] == 6 and meta["counts"] == [3, 3]

    from flaxdiff_tpu.data.packed_records import PackedRecordReader
    reader = PackedRecordReader(str(out / "shard-00000.pack"))
    assert len(reader) == 3
    rec = reader[0]
    assert rec["txt"].decode() == "roses"
    img = cv2.imdecode(np.frombuffer(rec["jpg"], np.uint8),
                       cv2.IMREAD_COLOR)
    assert img is not None and min(img.shape[:2]) == 16

"""Training-free activation cache for DiT-family sampling.

Adjacent sampler timesteps produce highly redundant deep-block
activations (Just-in-Time / DeepCache, PAPERS.md): across one denoising
step the deep trunk's *residual contribution* changes far more slowly
than the input tokens do. A `CachePlan` exploits that without any
retraining: shallow blocks always run, and on non-refresh steps the
deep trunk is replaced by a cached residual delta re-centered on the
fresh shallow activations:

    refresh step:   out = tail(deep(shallow(x)))
                    taps = deep(shallow(x)) - shallow(x)     (recorded)
    cached step:    out = tail(shallow(x) + taps)            (reused)

Everything here is HOST-SIDE and static: the plan is a frozen,
hashable dataclass; its per-step refresh schedule is a numpy bool
array computed once per trajectory and folded into the sampling scan
as an input (`DiffusionSampler._get_program` branches with a
`lax.cond` on the per-step flag — branch-local gating, no host syncs,
no global reductions). Model support is the `cache_mode` forward
contract (models/dit.py, models/uvit.py, models/mmdit.py):

    apply(params, x, t, c, cache_mode="record", cache_split=k)
        -> (out, taps)
    apply(params, x, t, c, cache_mode="reuse",  cache_split=k,
          cache_taps=taps) -> out

See docs/CACHING.md for plan semantics and the measured
quality/latency trade-off table.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional, Tuple

import numpy as np


@dataclasses.dataclass(frozen=True)
class CachePlan:
    """Static per-trajectory refresh schedule + depth split.

    refresh_every   full model evaluation every k-th trajectory step;
                    the steps in between reuse the cached deep delta.
                    1 = refresh every step (bit-identical to no cache,
                    tested), 3 = the default 2x-ish compute cut.
    depth_fraction  fraction of the transformer trunk that ALWAYS runs
                    (the shallow part the reuse step re-centers on).
                    Models map it to a concrete block split with
                    `cache_split_index` (U-shaped models count both
                    sides of the U).
    refresh_head    first N steps always refresh — early steps move the
                    trajectory the most and fill the cache (step 0 is
                    unconditionally a refresh regardless of this knob:
                    the cache starts empty).
    refresh_tail    last N steps always refresh — terminal detail is
                    where reuse error would be most visible.
    """

    enabled: bool = True
    refresh_every: int = 3
    depth_fraction: float = 0.2
    refresh_head: int = 2
    refresh_tail: int = 1

    def __post_init__(self):
        if self.refresh_every < 1:
            raise ValueError("refresh_every must be >= 1")
        if not 0.0 < self.depth_fraction < 1.0:
            raise ValueError("depth_fraction must be in (0, 1)")
        if self.refresh_head < 0 or self.refresh_tail < 0:
            raise ValueError("refresh_head/refresh_tail must be >= 0")

    def flags(self, num_steps: int) -> np.ndarray:
        """[num_steps] bool, True = full evaluation at that trajectory
        step. Step 0 is always True (the cache starts empty); disabled
        plans refresh everywhere."""
        if num_steps < 1:
            raise ValueError("num_steps must be >= 1")
        if not self.enabled:
            return np.ones((num_steps,), dtype=bool)
        idx = np.arange(num_steps)
        flags = (idx % self.refresh_every) == 0
        flags |= idx < max(1, self.refresh_head)
        if self.refresh_tail:
            flags |= idx >= num_steps - self.refresh_tail
        flags[0] = True
        return flags

    def key(self) -> Tuple:
        """Hashable identity for compiled-program cache keys: two
        different plans must never share a program."""
        return ("diffcache", self.enabled, self.refresh_every,
                self.depth_fraction, self.refresh_head,
                self.refresh_tail)

    def reused_fraction(self, num_steps: int) -> float:
        """Fraction of trajectory steps served from the cache."""
        f = self.flags(num_steps)
        return float((~f).sum()) / float(num_steps)


# the serving layer's per-request default when a request asks for
# caching without a specific plan; also the bench stage's headline plan
DEFAULT_CACHE_PLAN = CachePlan()


def active_plan(plan: Optional[CachePlan]) -> Optional[CachePlan]:
    """None unless the plan is present, enabled, and can actually reuse
    something. `refresh_every=1` refreshes every step for ANY
    trajectory length, so the optimal implementation IS the plain
    uncached program — routing it there makes the always-refresh plan
    bit-identical to pre-cache sampling BY CONSTRUCTION at every model
    scale (XLA may tile the cached program's `cond` branches
    differently from the inline program, so running the cached
    machinery with all-True flags is only exact-to-rounding), and
    drops the dead taps carry."""
    if plan is None or not plan.enabled or plan.refresh_every == 1:
        return None
    return plan


def model_supports_cache(model: Any,
                         plan: Optional[CachePlan] = None) -> bool:
    """A model supports the cache when it implements the `cache_mode`
    forward contract AND can actually split at the plan's depth (a
    1-layer DiT has no deep trunk to cache)."""
    if not hasattr(model, "cache_split_index"):
        return False
    frac = (plan.depth_fraction if plan is not None
            else DEFAULT_CACHE_PLAN.depth_fraction)
    try:
        model.cache_split_index(frac)
    except ValueError:
        return False
    return True


def resolve_cache_fns(model: Any, plan: CachePlan
                      ) -> Tuple[Callable, Callable]:
    """(record_fn, reuse_fn) closures over the model's `cache_mode`
    forward for `DiffusionSampler(cache_fns=...)`:

        record_fn(params, x, t, cond) -> (raw, taps)
        reuse_fn(params, x, t, cond, taps) -> raw

    Raises ValueError when the model cannot honor the plan.
    """
    if not hasattr(model, "cache_split_index"):
        raise ValueError(
            f"{type(model).__name__} does not implement the cache_mode "
            f"forward contract (docs/CACHING.md); diffusion caching "
            f"supports the DiT/UDiT/MM-DiT families")
    split = model.cache_split_index(plan.depth_fraction)

    def record_fn(params, x, t, cond):
        return model.apply(params, x, t, cond, cache_mode="record",
                           cache_split=split)

    def reuse_fn(params, x, t, cond, taps):
        return model.apply(params, x, t, cond, cache_mode="reuse",
                           cache_split=split, cache_taps=taps)

    return record_fn, reuse_fn

#!/usr/bin/env python
"""Offline checkpoint-integrity audit (resilience/verify.py CLI).

A corrupt orbax step dir is listed by `all_steps()` like a good one and
only fails at restore time — run this BEFORE pointing a pod job at a
checkpoint directory, or after any run that logged `save_failed` /
`fallback_restore` / `commit_aborted` resilience events.

Usage:
    python scripts/verify_checkpoint.py runs/ckpt              # latest step
    python scripts/verify_checkpoint.py runs/ckpt --all        # every step
    python scripts/verify_checkpoint.py runs/ckpt --step 400 --deep
    python scripts/verify_checkpoint.py runs/ckpt --json
    python scripts/verify_checkpoint.py runs/ckpt --all-steps --json

`--deep` additionally restores every leaf to host numpy (topology-free)
and flags non-finite tensors. `--all-steps --json` is the fleet-debug
mode for asymmetric corruption: run it on every host and diff — it
prints ONE JSON object holding per-step validity plus the step-ledger
commit status (docs/RESILIENCE.md), the exact inputs each host brings
to a consensus restore. Exit code 0 iff every checked step is intact.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("directory", help="checkpoint directory (orbax layout)")
    ap.add_argument("--step", type=int, default=None,
                    help="check this step only (default: latest)")
    ap.add_argument("--all", action="store_true", dest="all_steps",
                    help="check every step dir")
    ap.add_argument("--all-steps", action="store_true", dest="combined",
                    help="check every step AND report ledger commit "
                         "status; with --json, one combined object "
                         "(fleet-wide asymmetric-corruption debugging)")
    ap.add_argument("--deep", action="store_true",
                    help="restore every leaf to host numpy and check "
                         "finiteness (slower; needs jax+orbax)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable report on stdout")
    args = ap.parse_args(argv)

    from flaxdiff_tpu.resilience.verify import (annotate_ledger,
                                                verify_checkpoint)
    reports = verify_checkpoint(args.directory, step=args.step,
                                deep=args.deep,
                                all_steps=args.all_steps or args.combined)
    ledger = annotate_ledger(args.directory, reports)
    ok = all(r.ok and not r.nonfinite_leaves for r in reports)

    if args.as_json and args.combined:
        # one object per host: diff these across the fleet to localize
        # which host disagrees about which step
        print(json.dumps({
            "directory": args.directory,
            "ok": ok,
            "ledger": ledger,
            "steps": [r.as_dict() for r in reports],
        }, indent=2))
    elif args.as_json:
        print(json.dumps([r.as_dict() for r in reports], indent=2))
    else:
        if args.combined:
            if ledger["present"]:
                print(f"ledger: {len(ledger['committed_steps'])} committed "
                      f"step(s) {ledger['committed_steps']} "
                      f"({ledger['entries']} entries)")
                for w in ledger.get("world_changes", []):
                    print(f"world:  {w.get('change')} -> "
                          f"{w.get('world')} host(s) "
                          f"{w.get('members')} from step {w.get('step')} "
                          f"(epoch {w.get('epoch')}; "
                          f"{w.get('reason', '')})")
            else:
                print("ledger: none (pre-coordination checkpoint dir)")
        for r in reports:
            status = "OK " if r.ok else "BAD"
            extra = f", {r.n_leaves} leaves" if r.n_leaves is not None else ""
            if r.committed is not None:
                extra += (", committed" if r.committed else ", UNCOMMITTED")
            print(f"[{status}] step {r.step}: {r.n_files} files, "
                  f"{r.n_bytes} bytes{extra}")
            for err in r.errors:
                print(f"      - {err}")
            for leaf in r.nonfinite_leaves:
                print(f"      - non-finite values in {leaf}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())

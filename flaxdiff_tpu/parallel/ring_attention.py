"""Ring attention: exact sequence-parallel attention over a mesh axis.

The reference has NO sequence parallelism of any kind (SURVEY.md §5.7);
this is the TPU-native extension that lifts the single-device sequence
bound. Algorithm (Liu et al. 2023, Ring Attention with Blockwise
Transformers): each device holds one sequence shard of Q and of K/V; K/V
shards rotate around the ring via `jax.lax.ppermute` while every device
accumulates its Q-shard's attention, so the full [S, S] score matrix is
never materialized and communication overlaps compute on the ICI ring.

The LOCAL block per hop is itself blockwise (VERDICT r2 weak #3): on TPU
it runs the first-party Pallas flash kernel (ops/flash_attention.py),
elsewhere a chunked online softmax — per-hop live memory is
O(block·d), not O((S/n)²), so the long-context video workloads that
justify ring attention actually fit. Per-hop partial outputs merge
across hops through their logsumexp weights:

    out = Σ_h o_h · exp(lse_h − lse_total),  lse_total = logaddexp_h lse_h

which is exactly full-softmax attention over the whole sequence.

The whole sharded body is one `jax.custom_vjp`: the backward pass
re-rotates K/V around the ring and recomputes probabilities blockwise
from the saved global (out, lse) — the flash-backward decomposition is
exact per K/V block given global lse and delta = rowsum(dO·O) — with
dK/dV accumulators riding the ring home. Nothing per-hop is stored, so
backward memory is O(S/n·d) too (plain AD through the forward loop would
have stashed every visiting K/V shard = the full sequence per device).

Exactness (fwd + grads) is verified against the XLA path in
tests/test_ring_attention.py, including a 16k-token-per-shard case.
"""
from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map

_LANES = 128
_DEFAULT_CHUNK = 1024


def _use_flash_kernel() -> bool:
    from ..ops.attention import attention_backend_available
    return attention_backend_available("flash")


# ---------------------------------------------------------------------------
# Per-hop local attention: (o, lse) of q against ONE visiting K/V shard
# ---------------------------------------------------------------------------

def _hop_fwd_flash(q, k, v, scale, interpret=False):
    """Pallas path: full flash forward with residuals. Returns
    (o [B,Sq,H,D] f32, lse [B,H,Sq] f32)."""
    from ..ops.flash_attention import _from_bh, _fwd_impl, _to_bh
    B, Sq, H, D = q.shape
    pad_d = 0 if interpret else (-D) % _LANES
    if pad_d:
        widths = ((0, 0), (0, 0), (0, 0), (0, pad_d))
        q, k, v = (jnp.pad(t, widths) for t in (q, k, v))
    # _fwd_impl operates on the kernel's [B*H, L, D] layout
    out_bh, lse_bh = _fwd_impl(_to_bh(q), _to_bh(k), _to_bh(v), scale,
                               128, 128, interpret, save_residuals=True)
    o = _from_bh(out_bh, B, H)[:, :Sq, :, :D].astype(jnp.float32)
    lse = lse_bh[:, :Sq, 0].reshape(B, H, Sq)
    return o, lse


def _hop_fwd_chunked(q, k, v, scale, chunk):
    """Chunked online softmax (any backend). Returns (o f32, lse)."""
    B, Sq, H, D = q.shape
    Skv = k.shape[1]
    chunk = min(chunk, Skv)
    pad = (-Skv) % chunk
    if pad:
        widths = ((0, 0), (0, pad), (0, 0), (0, 0))
        k = jnp.pad(k, widths)
        v = jnp.pad(v, widths)
    nb = k.shape[1] // chunk
    kb = k.reshape(B, nb, chunk, H, D).swapaxes(0, 1)
    vb = v.reshape(B, nb, chunk, H, D).swapaxes(0, 1)

    o0 = (q * 0).astype(jnp.float32)
    l0 = jnp.sum(o0, axis=-1).transpose(0, 2, 1)        # [B, H, Sq]
    m0 = l0 - jnp.inf

    def body(carry, inp):
        o, l, m = carry
        kc, vc, idx = inp
        s = jnp.einsum("bqhd,bkhd->bhqk", q, kc,
                       preferred_element_type=jnp.float32) * scale
        kv_pos = idx * chunk + jax.lax.broadcasted_iota(jnp.int32, s.shape, 3)
        s = jnp.where(kv_pos < Skv, s, -jnp.inf)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[..., None])
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1)
        pv = jnp.einsum("bhqk,bkhd->bqhd", p, vc.astype(jnp.float32),
                        preferred_element_type=jnp.float32)
        o_new = o * corr.transpose(0, 2, 1)[..., None] + pv
        return (o_new, l_new, m_new), ()

    (o, l, m), _ = jax.lax.scan(body, (o0, l0, m0),
                                (kb, vb, jnp.arange(nb)))
    l = jnp.maximum(l, 1e-30)
    return o / l.transpose(0, 2, 1)[..., None], m + jnp.log(l)


def _hop_bwd_flash(q, k, v, g, out, lse, scale, interpret=False):
    """Pallas path: per-hop (dq_contrib, dk, dv) for one visiting K/V
    shard, from GLOBAL out/lse (the flash backward decomposition is exact
    per block given global lse and delta)."""
    from ..ops.flash_attention import _block_sizes, _bwd_impl, _to_bh
    B, Sq, H, D = q.shape
    Skv = k.shape[1]
    pad_d = 0 if interpret else (-D) % _LANES
    if pad_d:
        widths = ((0, 0), (0, 0), (0, 0), (0, pad_d))
        q, k, v, g, out = (jnp.pad(t, widths) for t in (q, k, v, g, out))
    out_bh = _to_bh(out)
    # lane-replicated lse in kernel layout, q rows padded to the block
    # (pad value 0 is safe: padded g/out rows are zero, so their ds and
    # dv contributions vanish; padded dq rows are sliced off)
    bq, _ = _block_sizes(Sq, Skv, 128, 128, interpret)
    lanes = 1 if interpret else _LANES
    lse_bh = lse.reshape(B * H, Sq, 1)
    pad_q = (-Sq) % bq
    if pad_q:
        lse_bh = jnp.pad(lse_bh, ((0, 0), (0, pad_q), (0, 0)))
    lse_bh = jnp.broadcast_to(lse_bh, lse_bh.shape[:2] + (lanes,))
    # _bwd_impl operates on (and returns) the kernel's [B*H, L, D]
    # layout; hop results go back to [B, L, H, D] for the ring carries
    from ..ops.flash_attention import _from_bh
    dq3, dk3, dv3 = _bwd_impl(_to_bh(q), _to_bh(k), _to_bh(v), out_bh,
                              lse_bh, _to_bh(g), scale, 128, 128,
                              interpret=interpret)
    dq = _from_bh(dq3, B, H)
    dk = _from_bh(dk3, B, H)
    dv = _from_bh(dv3, B, H)
    return (dq[..., :D].astype(jnp.float32),
            dk[:, :Skv, :, :D].astype(jnp.float32),
            dv[:, :Skv, :, :D].astype(jnp.float32))


def _hop_bwd_chunked(q, k, v, g, out, lse, scale, chunk):
    """Chunked per-hop backward (any backend): O(Sq·chunk) live memory."""
    B, Sq, H, D = q.shape
    Skv = k.shape[1]
    chunk = min(chunk, Skv)
    pad = (-Skv) % chunk
    if pad:
        widths = ((0, 0), (0, pad), (0, 0), (0, 0))
        k = jnp.pad(k, widths)
        v = jnp.pad(v, widths)
    nb = k.shape[1] // chunk
    kb = k.reshape(B, nb, chunk, H, D).swapaxes(0, 1)
    vb = v.reshape(B, nb, chunk, H, D).swapaxes(0, 1)

    gf = g.astype(jnp.float32)
    delta = jnp.sum(gf * out.astype(jnp.float32), axis=-1)   # [B, Sq, H]
    delta = delta.transpose(0, 2, 1)                          # [B, H, Sq]
    dq0 = (q * 0).astype(jnp.float32)

    def body(dq_acc, inp):
        kc, vc, idx = inp
        s = jnp.einsum("bqhd,bkhd->bhqk", q, kc,
                       preferred_element_type=jnp.float32) * scale
        kv_pos = idx * chunk + jax.lax.broadcasted_iota(jnp.int32, s.shape, 3)
        s = jnp.where(kv_pos < Skv, s, -jnp.inf)
        p = jnp.exp(s - lse[..., None])                       # global lse
        dv_c = jnp.einsum("bhqk,bqhd->bkhd", p, gf,
                          preferred_element_type=jnp.float32)
        dp = jnp.einsum("bqhd,bkhd->bhqk", gf, vc.astype(jnp.float32),
                        preferred_element_type=jnp.float32)
        ds = p * (dp - delta[..., None]) * scale
        dq_acc = dq_acc + jnp.einsum("bhqk,bkhd->bqhd", ds,
                                     kc.astype(jnp.float32),
                                     preferred_element_type=jnp.float32)
        dk_c = jnp.einsum("bhqk,bqhd->bkhd", ds, q.astype(jnp.float32),
                          preferred_element_type=jnp.float32)
        return dq_acc, (dk_c, dv_c)

    dq, (dk_b, dv_b) = jax.lax.scan(body, dq0, (kb, vb, jnp.arange(nb)))
    dk = dk_b.swapaxes(0, 1).reshape(B, nb * chunk, H, D)[:, :Skv]
    dv = dv_b.swapaxes(0, 1).reshape(B, nb * chunk, H, D)[:, :Skv]
    return dq, dk, dv


# ---------------------------------------------------------------------------
# The ring (inside shard_map) as one custom_vjp
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def ring_attention_sharded(q: jax.Array, k: jax.Array, v: jax.Array,
                           axis_name: str, scale: Optional[float] = None,
                           chunk: int = _DEFAULT_CHUNK,
                           use_flash: Optional[bool] = None,
                           interpret: bool = False) -> jax.Array:
    """Body to be called INSIDE shard_map: q/k/v are the local sequence
    shards [B, S_local, H, D]; the sequence axis is sharded over
    `axis_name`. Returns the local shard of the attention output.

    use_flash: None = auto (Pallas kernel on TPU, chunked elsewhere);
    True with interpret=True runs the kernel in interpret mode so the
    flash hop plumbing is testable on CPU."""
    out, _ = _ring_fwd_impl(q, k, v, axis_name, scale, chunk, use_flash,
                            interpret)
    return out


def _ring_fwd_impl(q, k, v, axis_name, scale, chunk, use_flash=None,
                   interpret=False):
    D = q.shape[-1]
    scale = scale if scale is not None else D ** -0.5
    n = jax.lax.psum(1, axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]
    if use_flash is None:
        use_flash = _use_flash_kernel()

    # Derive the zero-init carry from q so it inherits q's full set of
    # device-varying axes (shard_map's varying-axis checker requires the
    # fori_loop carry type to match the accumulator outputs exactly).
    o0 = (q * 0).astype(jnp.float32)                      # [B, Sq, H, D]
    lse0 = jnp.sum(o0, axis=-1).transpose(0, 2, 1) - jnp.inf   # [B, H, Sq]

    def step(i, state):
        o, lse, k_cur, v_cur = state
        if use_flash:
            o_h, lse_h = _hop_fwd_flash(q, k_cur, v_cur, scale, interpret)
        else:
            o_h, lse_h = _hop_fwd_chunked(q, k_cur, v_cur, scale, chunk)
        # merge the hop's partial attention through logsumexp weights
        lse_new = jnp.logaddexp(lse, lse_h)
        w_old = jnp.exp(lse - lse_new).transpose(0, 2, 1)[..., None]
        w_new = jnp.exp(lse_h - lse_new).transpose(0, 2, 1)[..., None]
        o = o * w_old + o_h * w_new
        # rotate K/V one hop around the ring; the last rotation is wasted
        # but keeps the loop body uniform.
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return o, lse_new, k_nxt, v_nxt

    o, lse, _, _ = jax.lax.fori_loop(0, n, step, (o0, lse0, k, v))
    return o.astype(q.dtype), lse


def _ring_fwd_rule(q, k, v, axis_name, scale, chunk, use_flash, interpret):
    out, lse = _ring_fwd_impl(q, k, v, axis_name, scale, chunk, use_flash,
                              interpret)
    return out, (q, k, v, out, lse)


def _ring_bwd_rule(axis_name, scale, chunk, use_flash, interpret, res, g):
    q, k, v, out, lse = res
    D = q.shape[-1]
    scale = scale if scale is not None else D ** -0.5
    n = jax.lax.psum(1, axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]
    if use_flash is None:
        use_flash = _use_flash_kernel()

    dq0 = (q * 0).astype(jnp.float32)
    dk0 = (k * 0).astype(jnp.float32)
    dv0 = (v * 0).astype(jnp.float32)

    def step(i, state):
        dq, dk_acc, dv_acc, k_cur, v_cur = state
        if use_flash:
            dq_h, dk_h, dv_h = _hop_bwd_flash(q, k_cur, v_cur, g, out,
                                              lse, scale, interpret)
        else:
            dq_h, dk_h, dv_h = _hop_bwd_chunked(q, k_cur, v_cur, g, out,
                                                lse, scale, chunk)
        dq = dq + dq_h
        dk_acc = dk_acc + dk_h
        dv_acc = dv_acc + dv_h
        # dK/dV accumulators ride the ring WITH their K/V shard: after n
        # add-then-rotate hops every shard (and its gradient) is home.
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        dk_nxt = jax.lax.ppermute(dk_acc, axis_name, perm)
        dv_nxt = jax.lax.ppermute(dv_acc, axis_name, perm)
        return dq, dk_nxt, dv_nxt, k_nxt, v_nxt

    dq, dk, dv, _, _ = jax.lax.fori_loop(0, n, step,
                                         (dq0, dk0, dv0, k, v))
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


ring_attention_sharded.defvjp(_ring_fwd_rule, _ring_bwd_rule)


# ---------------------------------------------------------------------------
# shard_map wrappers
# ---------------------------------------------------------------------------

def seq_shard_spec(mesh: Mesh, seq_axis: str = "seq",
                   batch_axes: Tuple[str, ...] = ("data",)) -> P:
    """PartitionSpec for [B, S, H, D] with S on the seq axis (shared by
    the ring and Ulysses shard_map wrappers)."""
    b_spec = tuple(a for a in batch_axes if a in mesh.axis_names)
    b = b_spec if len(b_spec) != 1 else b_spec[0]
    return P(b if b_spec else None, seq_axis, None, None)


def ring_self_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                        mesh: Mesh, seq_axis: str = "seq",
                        batch_axes: Tuple[str, ...] = ("data",),
                        scale: Optional[float] = None) -> jax.Array:
    """Top-level entry: [B, S, H, D] arrays, S sharded over `seq_axis`,
    B over `batch_axes`. Wraps `ring_attention_sharded` in shard_map so
    XLA SPMD emits the ppermute ring over ICI."""
    spec = seq_shard_spec(mesh, seq_axis, batch_axes)

    def body(q, k, v):   # custom_vjp args must be positional
        return ring_attention_sharded(q, k, v, seq_axis, scale,
                                      _DEFAULT_CHUNK, None, False)
    kwargs = dict(mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    try:
        # pallas_call primitives carry no varying-axis info; skip the check
        fn = shard_map(body, check_vma=False, **kwargs)
    except TypeError:
        fn = shard_map(body, check_rep=False, **kwargs)
    return fn(q, k, v)


def sequence_sharding(mesh: Mesh, seq_axis: str = "seq",
                      batch_axes: Tuple[str, ...] = ("data",)
                      ) -> NamedSharding:
    """NamedSharding for [B, S, ...] activations with S on the seq axis."""
    b_spec = tuple(a for a in batch_axes if a in mesh.axis_names)
    b = b_spec if len(b_spec) != 1 else b_spec[0]
    return NamedSharding(mesh, P(b if b_spec else None, seq_axis))

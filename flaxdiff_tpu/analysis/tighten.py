"""`scripts/lint.py --tighten`: act on every shrink note in one command.

The framework has always SAID when a budget carried slack ("N findings,
budget M — shrink"); acting on the notes was a hand-edit. This module
computes the tightened budget tables from a finished Report and
serializes a fresh `budgets.py`, so the whole loop is:

    python scripts/lint.py --tighten        # rewrite budgets.py
    python scripts/lint.py                  # re-lints clean, zero notes

Semantics (deliberately one-directional):

- ALLOWLIST entries are set to min(old budget, observed count) — tighten
  never RAISES a budget (an over-budget run keeps failing; masking a
  regression is a hand-edit and a review event) and never ADDS a file
  that wasn't grandfathered. Entries that reach 0 are dropped.
- UPCAST_BUDGET pins are set to the observed element count (exact: the
  traces are deterministic, so drift only happens when code changes —
  at which point the failure is the feature).
- COMM_BUDGET pins are set to observed comm bytes, and every program
  with nonzero collective traffic that wasn't pinned yet GAINS a pin —
  pinning is tightening (it was unlimited before).

Only rules that actually RAN in the report are touched: a scoped run
(`--rules host-sync --tighten`) rewrites host-sync budgets and leaves
everything else byte-identical.
"""
from __future__ import annotations

from typing import Dict, List, Tuple

from .framework import Report

BUDGET_HEADER = '''\
"""Machine-editable budget tables for the graph-hygiene analyzer.

Split out of framework.py so `python scripts/lint.py --tighten` can
rewrite the numbers mechanically (the framework emits shrink/stale
notes; tighten acts on every one of them in one command). framework.py
re-exports these names, so `framework.ALLOWLIST` etc. keep working —
the dicts here are THE live objects, not copies.

Hand-edit only to RAISE a budget deliberately (a review event: say in
the PR why the new debt is load-bearing); shrinking is what --tighten
is for. Semantics live in framework.py (`apply_budgets`) and
docs/ANALYSIS.md "Allowlist policy".
"""
from typing import Dict
'''


def observed_counts(report: Report) -> Dict[Tuple[str, str], int]:
    counts: Dict[Tuple[str, str], int] = {}
    for f in report.findings:
        counts[(f.rule, f.file)] = counts.get((f.rule, f.file), 0) + 1
    return counts


def tightened_budgets(report: Report,
                      allowlist: Dict[str, Dict[str, int]],
                      upcast: Dict[str, int],
                      comm: Dict[str, int]
                      ) -> Tuple[Dict[str, Dict[str, int]],
                                 Dict[str, int], Dict[str, int],
                                 List[str]]:
    """(new_allowlist, new_upcast, new_comm, change descriptions)."""
    ran = set(report.rules_run)
    counts = observed_counts(report)
    changes: List[str] = []

    new_allow: Dict[str, Dict[str, int]] = {}
    for rule, files in allowlist.items():
        if rule not in ran:
            new_allow[rule] = dict(files)
            continue
        kept: Dict[str, int] = {}
        for file, budget in files.items():
            observed = counts.get((rule, file), 0)
            new = min(budget, observed)
            if new != budget:
                changes.append(f"{rule}/{file}: {budget} -> {new}"
                               + ("" if new else " (dropped)"))
            if new > 0:
                kept[file] = new
        new_allow[rule] = kept

    new_upcast = dict(upcast)
    if "bf16-upcast" in ran:
        for prog, budget in upcast.items():
            st = report.graph_stats.get(prog, {}).get("bf16-upcast")
            if not st:
                continue
            observed = int(st.get("elements", budget))
            new = min(budget, observed)
            if new != budget:
                changes.append(f"UPCAST_BUDGET[{prog!r}]: "
                               f"{budget} -> {new}")
                new_upcast[prog] = new

    new_comm = dict(comm)
    if "collective-inventory" in ran:
        for prog, rules in sorted(report.graph_stats.items()):
            st = rules.get("collective-inventory")
            if not st:
                continue
            observed = int(st.get("comm_bytes", 0))
            if prog in new_comm:
                new = min(new_comm[prog], observed)
                if new != new_comm[prog]:
                    changes.append(f"COMM_BUDGET[{prog!r}]: "
                                   f"{new_comm[prog]} -> {new}")
                    new_comm[prog] = new
            elif observed > 0:
                changes.append(f"COMM_BUDGET[{prog!r}]: "
                               f"(unpinned) -> {observed}")
                new_comm[prog] = observed

    return new_allow, new_upcast, new_comm, changes


def _render_str_int_dict(d: Dict[str, int], indent: str) -> List[str]:
    return [f'{indent}"{k}": {d[k]},' for k in sorted(d)]


def render_budgets(allowlist: Dict[str, Dict[str, int]],
                   upcast: Dict[str, int],
                   comm: Dict[str, int]) -> str:
    """Serialize the three tables as a fresh budgets.py (stable order:
    rule registration order is not meaningful, so everything sorts)."""
    lines: List[str] = [BUDGET_HEADER]
    lines.append("# Per-(rule, file) finding-count MAXIMA. Empty dict "
                 "for a rule = zero")
    lines.append("# tolerance everywhere (the silent-except contract "
                 "since PR 9). Graph")
    lines.append('# rules budget by pseudo-file "jaxpr:<program>".')
    lines.append("ALLOWLIST: Dict[str, Dict[str, int]] = {")
    for rule in sorted(allowlist):
        files = allowlist[rule]
        if not files:
            lines.append(f'    "{rule}": {{}},')
        else:
            lines.append(f'    "{rule}": {{')
            lines.extend(_render_str_int_dict(files, "        "))
            lines.append("    },")
    lines.append("}")
    lines.append("")
    lines.append("# bf16 -> f32 upcast element budgets per traced "
                 "program (see framework.py")
    lines.append("# for the audit doctrine); unpinned programs are "
                 "report-only.")
    lines.append("UPCAST_BUDGET: Dict[str, int] = {")
    lines.extend(_render_str_int_dict(upcast, "    "))
    lines.append("}")
    lines.append("")
    lines.append("# Static comm-model budgets: estimated per-device "
                 "collective bytes per")
    lines.append("# execution of a traced program (analysis/"
                 "shard_rules.py documents the")
    lines.append("# byte model); unpinned programs are report-only.")
    lines.append("COMM_BUDGET: Dict[str, int] = {")
    lines.extend(_render_str_int_dict(comm, "    "))
    lines.append("}")
    lines.append("")
    return "\n".join(lines)

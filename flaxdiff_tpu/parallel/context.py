"""Active-mesh context: lets attention modules reach the device mesh.

Flax module trees are built from static config (strings, ints); a Mesh is
runtime state. The trainer/sampler declare the mesh once here and the
attention dispatch (`ops/attention.py` backend="ring") picks it up during
tracing — no mesh threading through every module constructor. This is the
TPU-native replacement for the reference's pattern of closing the mesh
over the train step (reference trainer/simple_trainer.py:176,413-415);
here any module can be sequence-parallel without its parent knowing.
"""
from __future__ import annotations

import contextlib
import contextvars
from typing import Optional

from jax.sharding import Mesh

_active_mesh: contextvars.ContextVar[Optional[Mesh]] = \
    contextvars.ContextVar("flaxdiff_tpu_active_mesh", default=None)
_seq_axis: contextvars.ContextVar[str] = \
    contextvars.ContextVar("flaxdiff_tpu_seq_axis", default="seq")


def set_active_mesh(mesh: Optional[Mesh], seq_axis: str = "seq"):
    """Declare the mesh (and sequence axis name) model code should use.
    Returns nothing; call with None to clear."""
    _active_mesh.set(mesh)
    _seq_axis.set(seq_axis)


def get_active_mesh() -> Optional[Mesh]:
    return _active_mesh.get()


def get_seq_axis() -> str:
    return _seq_axis.get()


@contextlib.contextmanager
def use_mesh(mesh: Mesh, seq_axis: str = "seq"):
    """Scoped variant of set_active_mesh."""
    tok_m = _active_mesh.set(mesh)
    tok_s = _seq_axis.set(seq_axis)
    try:
        yield mesh
    finally:
        _active_mesh.reset(tok_m)
        _seq_axis.reset(tok_s)


def seq_parallel_active() -> bool:
    """True when a mesh with a >1-sized sequence axis is declared."""
    mesh = get_active_mesh()
    axis = get_seq_axis()
    return (mesh is not None and axis in mesh.axis_names
            and mesh.shape[axis] > 1)

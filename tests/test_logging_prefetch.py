"""Direct tests for the logging layer and the prefetch pipeline."""
import json
import threading
import time

import numpy as np
import pytest

from flaxdiff_tpu.data.prefetch import prefetch_map, prefetch_to_device
from flaxdiff_tpu.trainer.logging import (JsonlLogger, MultiLogger,
                                          make_logger, save_image_grid)


class TestJsonlLogger:
    def test_log_coerces_numpy_scalars(self, tmp_path):
        lg = JsonlLogger(str(tmp_path / "log.jsonl"))
        lg.log({"loss": np.float32(0.5), "count": np.int64(3),
                "name": "run", "flag": True, "none": None,
                "small_array": np.zeros(3),
                "huge_array": np.zeros((64, 64))}, step=np.int32(7))
        lg.finish()
        rec = json.loads(open(tmp_path / "log.jsonl").read())
        assert rec["loss"] == 0.5 and isinstance(rec["loss"], float)
        assert rec["count"] == 3 and isinstance(rec["count"], int)
        assert rec["step"] == 7
        assert rec["name"] == "run" and rec["flag"] is True
        assert rec["none"] is None
        # small numeric sequences serialize inline (the pre-telemetry
        # logger dropped EVERY non-scalar silently); oversized arrays
        # are still dropped, but counted — see test_telemetry.py
        assert rec["small_array"] == [0.0, 0.0, 0.0]
        assert "huge_array" not in rec
        assert "_time" in rec

    def test_log_images_writes_png_and_reference(self, tmp_path):
        lg = JsonlLogger(str(tmp_path / "log.jsonl"))
        imgs = np.random.default_rng(0).uniform(
            -1, 1, (5, 8, 8, 3)).astype(np.float32)
        lg.log_images("val/samples", imgs, step=12)
        lg.finish()
        rec = json.loads(open(tmp_path / "log.jsonl").read())
        png = rec["val/samples"]
        assert png.endswith("val_samples_000012.png")
        import cv2
        grid = cv2.imread(png)
        # 5 images -> 3x2 grid of 8px tiles with 2px pad
        assert grid is not None and grid.shape == (18, 28, 3)

    def test_log_images_failure_never_raises(self, tmp_path):
        lg = JsonlLogger(str(tmp_path / "log.jsonl"))
        lg.log_images("bad", np.zeros((2, 3)), step=0)   # wrong rank
        lg.finish()
        rec = json.loads(open(tmp_path / "log.jsonl").read())
        assert "grid save failed" in rec["bad"]


def test_save_image_grid_video_input(tmp_path):
    vids = np.random.default_rng(0).integers(
        0, 255, (2, 3, 8, 8, 3)).astype(np.uint8)
    path = save_image_grid(vids, str(tmp_path / "g.png"))
    import cv2
    grid = cv2.imread(path)
    # 6 frames -> 3x2 grid
    assert grid.shape == (18, 28, 3)


def test_make_logger_fallbacks(tmp_path):
    lg = make_logger(jsonl_path=str(tmp_path / "a.jsonl"))
    assert isinstance(lg, JsonlLogger)
    lg.finish()
    # wandb project + jsonl: wandb may be absent; never raises
    lg = make_logger(project=None, jsonl_path=str(tmp_path / "b.jsonl"))
    lg.log({"x": 1})
    lg.finish()


def test_multilogger_fans_out(tmp_path):
    a = JsonlLogger(str(tmp_path / "a.jsonl"))
    b = JsonlLogger(str(tmp_path / "b.jsonl"))
    ml = MultiLogger([a, b])
    ml.log({"v": 2}, step=1)
    ml.finish()
    for f in ("a.jsonl", "b.jsonl"):
        assert json.loads(open(tmp_path / f).read())["v"] == 2


class TestPrefetchMap:
    def test_order_preserved(self):
        out = list(prefetch_map(lambda x: x * 2, iter(range(20)), depth=3))
        assert out == [x * 2 for x in range(20)]

    def test_fn_exception_propagates(self):
        def boom(x):
            if x == 3:
                raise RuntimeError("bad item")
            return x

        it = prefetch_map(boom, iter(range(10)), depth=2)
        assert next(it) == 0
        with pytest.raises(RuntimeError, match="bad item"):
            list(it)

    def test_source_exception_propagates(self):
        def src():
            yield 1
            raise ValueError("source died")

        it = prefetch_map(lambda x: x, src(), depth=2)
        assert next(it) == 1
        with pytest.raises(ValueError, match="source died"):
            next(it)

    def test_depth_validation(self):
        with pytest.raises(ValueError, match="depth"):
            list(prefetch_map(lambda x: x, iter([1]), depth=0))

    def test_actually_overlaps(self):
        """With depth 2, the producer works ahead while the consumer is
        slow: total wall time approaches max(produce, consume), not the
        sum."""
        def slow_fn(x):
            time.sleep(0.05)
            return x

        t0 = time.perf_counter()
        for _ in prefetch_map(slow_fn, iter(range(8)), depth=4):
            time.sleep(0.05)   # consumer work
        dt = time.perf_counter() - t0
        # serial would be ~0.8s; overlapped ~0.45s
        assert dt < 0.7, dt

    def test_tuple_items_pass_through(self):
        """2-tuples from fn must not be mistaken for the sentinel."""
        out = list(prefetch_map(lambda x: (x, x + 1), iter(range(4))))
        assert out == [(0, 1), (1, 2), (2, 3), (3, 4)]

    @staticmethod
    def _live_workers():
        return {t for t in threading.enumerate()
                if t.name == "flaxdiff-prefetch" and t.is_alive()}

    def _assert_no_new_workers(self, before, timeout=3.0):
        deadline = time.time() + timeout
        while self._live_workers() - before and time.time() < deadline:
            time.sleep(0.05)
        leaked = self._live_workers() - before
        assert not leaked, leaked

    def test_worker_thread_terminates(self):
        before = self._live_workers()
        list(prefetch_map(lambda x: x, iter(range(5))))
        self._assert_no_new_workers(before)

    def test_abandoned_iterator_stops_worker(self):
        """A consumer that walks away mid-stream must not leave the
        worker blocked on the full queue forever."""
        before = self._live_workers()
        it = prefetch_map(lambda x: x, iter(range(1000)), depth=2)
        assert next(it) == 0
        it.close()   # generator finalizer sets the stop flag
        self._assert_no_new_workers(before)


class TestPrefetchToDevice:
    """ISSUE 17 satellite: upload-prefetch regression tests — clean
    teardown with an in-flight raising put_fn, starvation surfacing
    through a depth-2 pipeline, and no stranded buffers on close."""

    @staticmethod
    def _live_workers():
        return {t for t in threading.enumerate()
                if t.name == "flaxdiff-put-batch" and t.is_alive()}

    def _assert_no_new_workers(self, before, timeout=3.0):
        deadline = time.time() + timeout
        while self._live_workers() - before and time.time() < deadline:
            time.sleep(0.05)
        leaked = self._live_workers() - before
        assert not leaked, leaked

    def test_close_with_raising_put_fn_no_leaked_worker(self):
        """close() while put_fn is mid-failure must not hang or leak the
        worker thread — the error path and the stop path race by design
        and both must terminate."""
        before = self._live_workers()

        def put_fn(x):
            if x >= 2:
                raise RuntimeError("device OOM during upload")
            return x

        pf = prefetch_to_device(put_fn, iter(range(100)), depth=2)
        assert next(pf) == 0
        pf.close()                       # worker may be raising right now
        self._assert_no_new_workers(before)
        # a closed pipeline never hands out a stale buffer
        with pytest.raises((StopIteration, RuntimeError)):
            next(pf)

    def test_put_fn_exception_reraises_at_next(self):
        before = self._live_workers()
        pf = prefetch_to_device(
            lambda x: 1 // x, iter([2, 1, 0, 5]), depth=2)
        assert next(pf) == 0
        assert next(pf) == 1
        with pytest.raises(ZeroDivisionError):
            next(pf)
        with pytest.raises(StopIteration):   # pipeline is dead, stays dead
            next(pf)
        self._assert_no_new_workers(before)

    def test_starvation_raise_surfaces_through_depth2_pipeline(self):
        """A starving source (starvation_action='raise' semantics) behind
        a depth-2 upload pipeline: the RuntimeError crosses the thread
        boundary to the consumer's next(), after the already-uploaded
        batches drain, and the worker terminates."""
        before = self._live_workers()

        def starving_source():
            yield {"n": 0}
            yield {"n": 1}
            raise RuntimeError("no batch within 1.0s (starvation)")

        pf = prefetch_to_device(lambda b: b, starving_source(), depth=2)
        assert next(pf)["n"] == 0
        assert next(pf)["n"] == 1
        with pytest.raises(RuntimeError, match="starvation"):
            next(pf)
        self._assert_no_new_workers(before)

    def test_close_discards_window_no_stranded_buffers(self):
        """In-flight accounting: after close(), submitted - delivered is
        the discarded window, bounded by depth + 1 — nothing stranded,
        nothing double-counted."""
        before = self._live_workers()
        depth = 2
        pf = prefetch_to_device(lambda x: x, iter(range(1000)),
                                depth=depth)
        for k in range(3):
            assert next(pf) == k
        pf.close()
        self._assert_no_new_workers(before)
        st = pf.state_dict()
        assert st["delivered"] == 3
        assert st["in_flight"] == st["submitted"] - st["delivered"]
        assert 0 <= st["in_flight"] <= depth + 1

    def test_screen_quarantines_and_counts(self):
        """The pre-upload screen skips poisoned batches BEFORE put_fn
        (no H2D copy), notes them in the quarantine journal, and the
        healthy stream arrives intact and in order."""
        from flaxdiff_tpu.data import QuarantineJournal

        uploaded = []

        def put_fn(x):
            uploaded.append(x)
            return x

        journal = QuarantineJournal()
        pf = prefetch_to_device(
            put_fn, iter(range(8)), depth=2,
            screen=lambda x: "poison" if x % 3 == 2 else None,
            quarantine=journal)
        assert list(pf) == [0, 1, 3, 4, 6, 7]
        assert uploaded == [0, 1, 3, 4, 6, 7]    # screened never uploaded
        st = pf.state_dict()
        assert st["screened_out"] == 2
        assert st["submitted"] == st["delivered"] == 6
        assert st["in_flight"] == 0
        assert len(journal) == 2
        assert all(e["source"] == "prefetch" for e in journal.entries())

#!/usr/bin/env python
"""(shim) Metric-name gate — now rule `metric-name` of the unified
analyzer (`flaxdiff_tpu/analysis/`, CLI `scripts/lint.py`).

Kept as a thin wrapper so existing invocations keep working; the rule
logic (literal + f-string-prefix instrument names checked against the
docs/OBSERVABILITY.md reference, `<placeholder>` wildcards) and the
allowlist live in the analysis package.

Usage:
    python scripts/check_metric_names.py                 # repo defaults
    python scripts/check_metric_names.py --root DIR --docs FILE
"""
from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="fail on metric names missing from the "
                    "OBSERVABILITY.md reference (shim over "
                    "`scripts/lint.py --rules metric-name`)")
    ap.add_argument("--root", default=None,
                    help="scan this file/tree with an EMPTY allowlist "
                         "(default: flaxdiff_tpu/)")
    ap.add_argument("--docs", default=None,
                    help="markdown file holding the metric reference "
                         "(default: docs/OBSERVABILITY.md)")
    args = ap.parse_args(argv)

    from flaxdiff_tpu.analysis.cli import main as lint_main
    fwd = ["--rules", "metric-name", "--no-graph"]
    if args.root is not None:
        fwd += ["--root", args.root]
    if args.docs is not None:
        fwd += ["--docs", args.docs]
    return lint_main(fwd)


if __name__ == "__main__":
    sys.exit(main())

"""PSNR and SSIM image-quality metrics.

The reference ships empty placeholder files for these
(reference flaxdiff/metrics/psnr.py and ssim.py are both 0 LoC,
SURVEY §2 "psnr.py/ssim.py/__init__.py are empty") — this module
implements them for real. Both are pure jittable functions over
batched NHWC (or video [B,T,H,W,C], flattened over frames) arrays in
[-1, 1], plus `EvaluationMetric` factories that score generated
samples against the paired `batch["sample"]` images — meaningful for
reconstruction-style evaluation (VAE validation, img2img), not for
unpaired generative sampling (use FID/CLIP there).

SSIM follows Wang et al. 2004: 11x11 Gaussian window (sigma 1.5),
K1=0.01, K2=0.03, per-channel, mean-pooled. Implemented with two 1-D
depthwise convolutions (separable Gaussian) so XLA maps it onto conv
units instead of an O(window²) dense filter.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .common import EvaluationMetric

_DATA_RANGE = 2.0  # images live in [-1, 1]


def _flatten_video(x: jnp.ndarray) -> jnp.ndarray:
    """[B,T,H,W,C] -> [B*T,H,W,C]; NHWC passes through."""
    if x.ndim == 5:
        return x.reshape((-1,) + x.shape[2:])
    return x


@jax.jit
def psnr(pred: jnp.ndarray, target: jnp.ndarray,
         data_range: float = _DATA_RANGE) -> jnp.ndarray:
    """Mean peak signal-to-noise ratio (dB) over the batch."""
    pred = _flatten_video(pred).astype(jnp.float32)
    target = _flatten_video(target).astype(jnp.float32)
    mse = jnp.mean((pred - target) ** 2, axis=(1, 2, 3))
    mse = jnp.maximum(mse, 1e-12)
    return jnp.mean(20.0 * jnp.log10(data_range) - 10.0 * jnp.log10(mse))


def _gaussian_kernel(size: int, sigma: float) -> np.ndarray:
    x = np.arange(size, dtype=np.float64) - (size - 1) / 2.0
    k = np.exp(-0.5 * (x / sigma) ** 2)
    return (k / k.sum()).astype(np.float32)


def _blur(x: jnp.ndarray, kernel: jnp.ndarray) -> jnp.ndarray:
    """Separable Gaussian blur, depthwise, VALID padding. x: [N,H,W,C]."""
    c = x.shape[-1]
    kh = jnp.tile(kernel.reshape(-1, 1, 1, 1), (1, 1, 1, c))
    kw = jnp.tile(kernel.reshape(1, -1, 1, 1), (1, 1, 1, c))
    dn = jax.lax.conv_dimension_numbers(x.shape, kh.shape,
                                        ("NHWC", "HWIO", "NHWC"))
    x = jax.lax.conv_general_dilated(x, kh, (1, 1), "VALID",
                                     dimension_numbers=dn, feature_group_count=c)
    x = jax.lax.conv_general_dilated(x, kw, (1, 1), "VALID",
                                     dimension_numbers=dn, feature_group_count=c)
    return x


@functools.partial(jax.jit, static_argnames=("window_size", "sigma"))
def ssim(pred: jnp.ndarray, target: jnp.ndarray,
         data_range: float = _DATA_RANGE, window_size: int = 11,
         sigma: float = 1.5) -> jnp.ndarray:
    """Mean structural similarity over the batch (Wang et al. 2004)."""
    pred = _flatten_video(pred).astype(jnp.float32)
    target = _flatten_video(target).astype(jnp.float32)
    if pred.shape[1] < window_size or pred.shape[2] < window_size:
        raise ValueError(
            f"images {pred.shape[1]}x{pred.shape[2]} smaller than the "
            f"{window_size}x{window_size} SSIM window")
    kernel = jnp.asarray(_gaussian_kernel(window_size, sigma))
    c1 = (0.01 * data_range) ** 2
    c2 = (0.03 * data_range) ** 2

    mu_p = _blur(pred, kernel)
    mu_t = _blur(target, kernel)
    mu_pp, mu_tt, mu_pt = mu_p * mu_p, mu_t * mu_t, mu_p * mu_t
    var_p = _blur(pred * pred, kernel) - mu_pp
    var_t = _blur(target * target, kernel) - mu_tt
    cov = _blur(pred * target, kernel) - mu_pt

    s = ((2.0 * mu_pt + c1) * (2.0 * cov + c2)
         / ((mu_pp + mu_tt + c1) * (var_p + var_t + c2)))
    return jnp.mean(s)


def _paired_pair(samples, batch: Optional[dict]):
    """(pred, target) as float32 [0,1] pairs; data_range is then 1.

    Generated samples are [-1,1] floats BY CONTRACT (the sampler's
    output space, samplers/common.py generate_samples) — map them with
    the fixed (x+1)/2, never the value heuristic, which would misread a
    bright batch with no pixel below ~0 as already [0,1]. The validation
    batch's 'sample' is whatever the loader yields (uint8 [0,255] from
    grain; normalization happens in-jit), so it goes through the shared
    range heuristic (utils.to_unit_float, same as FID/grid logging).
    """
    from ..utils import to_unit_float
    if not batch or "sample" not in batch:
        raise ValueError("psnr/ssim need a paired batch with a 'sample' key "
                         "(reconstruction-style evaluation)")
    target = to_unit_float(batch["sample"])
    pred = np.clip((np.asarray(samples, np.float32) + 1.0) / 2.0, 0.0, 1.0)
    pred = pred[: target.shape[0]]
    return pred, target[: pred.shape[0]]


def get_psnr_metric() -> EvaluationMetric:
    def fn(samples, batch):
        pred, target = _paired_pair(samples, batch)
        return float(psnr(jnp.asarray(pred), jnp.asarray(target),
                          data_range=1.0))
    return EvaluationMetric(function=fn, name="psnr", higher_is_better=True)


def get_ssim_metric() -> EvaluationMetric:
    def fn(samples, batch):
        pred, target = _paired_pair(samples, batch)
        return float(ssim(jnp.asarray(pred), jnp.asarray(target),
                          data_range=1.0))
    return EvaluationMetric(function=fn, name="ssim", higher_is_better=True)

"""Goodput/badput accounting: classify ALL wall-clock time of a
training job into productive training vs. named badput buckets, and
persist the running totals so they accumulate ACROSS job incarnations.

After PR 1–2 this framework survives faults; this ledger is how a run
accounts for them: "we trained for 31 h of a 36 h allocation — 2.1 h
compile, 1.6 h checkpoint commits, 0.8 h restarts, 0.5 h data stalls"
is the decomposition elastic-training systems (Pulse, arXiv:2606.19163)
evaluate against, and the prerequisite for every perf item on the
ROADMAP. Buckets the framework itself attributes:

    compile             first-step host+device time of a fit (jit)
    checkpoint_commit   save dispatch + two-phase commit rounds
    restart             restore-at-start / consensus-restore rounds
    data_stall          host blocked waiting on the input pipeline
    coordination_lost   commit rounds spent discovering a dead peer
    eval                in-loop validation/sampling

The set is open — `record_badput` accepts any bucket name. The
invariant (tested): productive + all badput sums to the attributed
wall-clock of the run within tolerance; `goodput_fraction` is
productive over that total.

Persistence mirrors the resilience `StepLedger` philosophy: a small
JSON file (`goodput.json`, atomic tmp+rename, process 0 only) beside
the run's telemetry so a coordinated restart RESUMES the account
instead of zeroing it — each load bumps `incarnations`, and totals are
reported cumulatively across the job's lives.
"""
from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Dict, Optional, Tuple

GOODPUT_FILENAME = "goodput.json"


class GoodputLedger:
    """Thread-safe productive/badput time account with cross-incarnation
    persistence. `path=None` keeps an in-memory account (the process-
    global default hub), same API, nothing on disk."""

    def __init__(self, path: Optional[str] = None, process_index: int = 0):
        self.path = path
        self.process_index = process_index
        self._lock = threading.Lock()
        self._productive = 0.0
        self._badput: Dict[str, float] = {}
        # ESTIMATED badput avoided by live recovery (elastic transitions
        # vs. their checkpoint-and-exit counterfactual). A separate
        # account, NOT part of the productive+badput=wall-clock
        # invariant: reclaimed seconds never happened — they are what a
        # restart WOULD have cost — so adding them to either side would
        # corrupt the attribution closure.
        self._reclaimed: Dict[str, float] = {}
        self._prior_productive = 0.0
        self._prior_badput: Dict[str, float] = {}
        self._prior_reclaimed: Dict[str, float] = {}
        self.incarnation = 1
        if path is not None and os.path.exists(path):
            try:
                with open(path, "r", encoding="utf-8") as f:
                    prior = json.load(f)
                prior_productive = float(prior.get("productive_s", 0.0))
                prior_badput = {
                    str(k): float(v)
                    for k, v in dict(prior.get("badput_s", {})).items()}
                prior_reclaimed = {
                    str(k): float(v)
                    for k, v in dict(prior.get("reclaimed_s", {})).items()}
                incarnation = int(prior.get("incarnations", 0)) + 1
            except (json.JSONDecodeError, ValueError, TypeError, OSError):
                # a torn write from a crashed incarnation: start a fresh
                # account rather than refuse to train. Parsed into
                # locals so a partial parse (productive_s readable,
                # badput_s corrupt) cannot leave prior productive time
                # with zeroed badput — all-or-nothing.
                pass
            else:
                self._prior_productive = prior_productive
                self._prior_badput = prior_badput
                self._prior_reclaimed = prior_reclaimed
                self.incarnation = incarnation

    # -- recording -----------------------------------------------------------
    def record_productive(self, seconds: float) -> None:
        with self._lock:
            self._productive += max(float(seconds), 0.0)

    def record_badput(self, bucket: str, seconds: float) -> None:
        s = max(float(seconds), 0.0)
        if s == 0.0:
            return
        with self._lock:
            self._badput[bucket] = self._badput.get(bucket, 0.0) + s

    def record_reclaimed(self, bucket: str, seconds: float) -> None:
        """Credit an elastic transition's estimated badput savings vs.
        its checkpoint-and-exit counterfactual
        (`ElasticWorldManager.reclaimed_estimate`). Kept OUT of the
        productive/badput closure — see `_reclaimed` above."""
        s = max(float(seconds), 0.0)
        if s == 0.0:
            return
        with self._lock:
            self._reclaimed[bucket] = self._reclaimed.get(bucket, 0.0) + s

    def reattribute(self, bucket: str, seconds: float) -> float:
        """Move up to `seconds` from a badput bucket into productive
        time; returns the amount actually moved. The fit loop's
        compile-badput heuristic attributes the first step of every
        program to `compile` AT THE TIME — a warm persistent
        compilation cache makes that first step an ordinary cheap step,
        which the loop detects only once it has steady-state steps to
        compare against, and then corrects here. Only time recorded by
        THIS incarnation can move (prior incarnations' attribution is
        settled history)."""
        s = max(float(seconds), 0.0)
        with self._lock:
            moved = min(s, self._badput.get(bucket, 0.0))
            if moved <= 0.0:
                return 0.0
            self._badput[bucket] -= moved
            if self._badput[bucket] <= 0.0:
                del self._badput[bucket]
            self._productive += moved
            return moved

    @contextlib.contextmanager
    def measure_badput(self, bucket: str, clock=time.perf_counter):
        t0 = clock()
        try:
            yield
        finally:
            self.record_badput(bucket, clock() - t0)

    # -- queries -------------------------------------------------------------
    def raw_counters(self) -> Tuple[float, Dict[str, float]]:
        """(productive, badput) recorded by THIS incarnation only —
        callers diff two calls for a per-fit delta."""
        with self._lock:
            return self._productive, dict(self._badput)

    def totals(self, cumulative: bool = True) -> Dict[str, object]:
        with self._lock:
            productive = self._productive
            badput = dict(self._badput)
            reclaimed = dict(self._reclaimed)
        if cumulative:
            productive += self._prior_productive
            for k, v in self._prior_badput.items():
                badput[k] = badput.get(k, 0.0) + v
            for k, v in self._prior_reclaimed.items():
                reclaimed[k] = reclaimed.get(k, 0.0) + v
        bad_total = sum(badput.values())
        total = productive + bad_total
        return {
            "incarnations": self.incarnation,
            "productive_s": productive,
            "badput_s": badput,
            "badput_total_s": bad_total,
            "reclaimed_s": reclaimed,
            "reclaimed_total_s": sum(reclaimed.values()),
            "total_s": total,
            "goodput_fraction": (productive / total) if total > 0 else None,
        }

    def snapshot(self) -> Dict[str, float]:
        """Flat metrics view for registry-style exports."""
        t = self.totals()
        out = {"goodput/productive_s": t["productive_s"],
               "goodput/total_s": t["total_s"],
               "goodput/incarnation": float(self.incarnation)}
        if t["goodput_fraction"] is not None:
            out["goodput/fraction"] = t["goodput_fraction"]
        for k, v in t["badput_s"].items():
            out[f"goodput/badput/{k}_s"] = v
        if t["reclaimed_total_s"]:
            out["goodput/reclaimed_s"] = t["reclaimed_total_s"]
            for k, v in t["reclaimed_s"].items():
                out[f"goodput/reclaimed/{k}_s"] = v
        return out

    # -- persistence ---------------------------------------------------------
    def persist(self) -> None:
        """Atomic cumulative write (process 0 only — the account is a
        job-level fact, and hosts' clocks agree to within skew that
        does not matter at goodput granularity)."""
        if self.path is None or self.process_index != 0:
            return
        t = self.totals(cumulative=True)
        payload = {"incarnations": self.incarnation,
                   "productive_s": t["productive_s"],
                   "badput_s": t["badput_s"],
                   "reclaimed_s": t["reclaimed_s"],
                   "updated": time.time()}
        os.makedirs(os.path.dirname(os.path.abspath(self.path)) or ".",
                    exist_ok=True)
        tmp = self.path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(payload, f, indent=2)
        os.replace(tmp, self.path)

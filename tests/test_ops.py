"""Pallas kernel correctness vs XLA references (interpret mode on CPU)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flaxdiff_tpu.ops.attention import _xla_attention
from flaxdiff_tpu.ops.flash_attention import flash_attention
from flaxdiff_tpu.ops.fused_norm import _xla_groupnorm_silu, fused_groupnorm_silu


@pytest.mark.parametrize("lq,lk", [(128, 128), (256, 77), (100, 100)])
def test_flash_attention_matches_xla(lq, lk):
    key = jax.random.PRNGKey(0)
    b, h, d = 2, 2, 32
    q = jax.random.normal(key, (b, lq, h, d))
    k = jax.random.normal(jax.random.fold_in(key, 1), (b, lk, h, d))
    v = jax.random.normal(jax.random.fold_in(key, 2), (b, lk, h, d))
    out_flash = flash_attention(q, k, v, None, 64, 64, True)
    out_ref = _xla_attention(q, k, v)
    np.testing.assert_allclose(out_flash, out_ref, rtol=2e-3, atol=2e-3)


def test_flash_attention_grad_matches_xla():
    key = jax.random.PRNGKey(3)
    q = jax.random.normal(key, (1, 64, 2, 16))
    k = jax.random.normal(jax.random.fold_in(key, 1), (1, 64, 2, 16))
    v = jax.random.normal(jax.random.fold_in(key, 2), (1, 64, 2, 16))

    g_flash = jax.grad(lambda q_: jnp.sum(
        flash_attention(q_, k, v, None, 32, 32, True) ** 2))(q)
    g_ref = jax.grad(lambda q_: jnp.sum(_xla_attention(q_, k, v) ** 2))(q)
    np.testing.assert_allclose(g_flash, g_ref, rtol=1e-3, atol=1e-3)


@pytest.mark.parametrize("lq,lk", [(128, 128), (200, 77)])
def test_flash_attention_all_grads_match_xla(lq, lk):
    """dq/dk/dv from the Pallas backward kernels vs the XLA VJP, including
    the cross-attention shape (padded kv with masked tail)."""
    key = jax.random.PRNGKey(11)
    b, h, d = 2, 2, 32
    q = jax.random.normal(key, (b, lq, h, d))
    k = jax.random.normal(jax.random.fold_in(key, 1), (b, lk, h, d))
    v = jax.random.normal(jax.random.fold_in(key, 2), (b, lk, h, d))
    g = jax.random.normal(jax.random.fold_in(key, 3), (b, lq, h, d))

    def loss(fn):
        return lambda q_, k_, v_: jnp.sum(fn(q_, k_, v_) * g)

    flash = lambda q_, k_, v_: flash_attention(q_, k_, v_, None, 64, 64, True)
    got = jax.grad(loss(flash), (0, 1, 2))(q, k, v)
    want = jax.grad(loss(_xla_attention), (0, 1, 2))(q, k, v)
    for name, a, b_ in zip(("dq", "dk", "dv"), got, want):
        np.testing.assert_allclose(a, b_, rtol=2e-3, atol=2e-3, err_msg=name)


def test_flash_attention_long_sequence_grad():
    """VERDICT r1 #2 done-criterion: gradients vs XLA at >= 8k tokens in
    interpret mode (blockwise backward, no [L, L] materialization)."""
    key = jax.random.PRNGKey(5)
    b, l, h, d = 1, 8192, 1, 64
    q = jax.random.normal(key, (b, l, h, d), jnp.float32)
    k = jax.random.normal(jax.random.fold_in(key, 1), (b, l, h, d))
    v = jax.random.normal(jax.random.fold_in(key, 2), (b, l, h, d))
    g = jax.random.normal(jax.random.fold_in(key, 3), (b, l, h, d))

    flash = lambda q_, k_, v_: flash_attention(q_, k_, v_, None, 1024, 1024,
                                               True)
    got = jax.grad(lambda *a: jnp.sum(flash(*a) * g), (0, 1, 2))(q, k, v)
    want = jax.grad(lambda *a: jnp.sum(_xla_attention(*a) * g),
                    (0, 1, 2))(q, k, v)
    for name, a, b_ in zip(("dq", "dk", "dv"), got, want):
        np.testing.assert_allclose(a, b_, rtol=5e-3, atol=5e-3, err_msg=name)


def test_flash_attention_native_head_dim_hw_lanes(monkeypatch):
    """Native sub-128 head_dim with the HARDWARE 128-lane scratch layout
    (interpret mode normally shrinks lanes to 1, which is why the
    (128, 64)x(128, 0) broadcast bug in _bcast only surfaced on a real
    chip — the r3 bench attnpad stage caught it). Forward and all grads
    vs XLA at d=64 with full-width lane-replicated scratch."""
    from flaxdiff_tpu.ops import flash_attention as fa
    monkeypatch.setattr(fa, "_FORCE_LANES", fa.LANES)
    key = jax.random.PRNGKey(7)
    b, l, h, d = 1, 256, 2, 64
    q = jax.random.normal(key, (b, l, h, d))
    k = jax.random.normal(jax.random.fold_in(key, 1), (b, l, h, d))
    v = jax.random.normal(jax.random.fold_in(key, 2), (b, l, h, d))
    g = jax.random.normal(jax.random.fold_in(key, 3), (b, l, h, d))

    flash = lambda q_, k_, v_: flash_attention(q_, k_, v_, None, 128, 128,
                                               True)
    np.testing.assert_allclose(flash(q, k, v), _xla_attention(q, k, v),
                               rtol=2e-3, atol=2e-3)
    got = jax.grad(lambda *a: jnp.sum(flash(*a) * g), (0, 1, 2))(q, k, v)
    want = jax.grad(lambda *a: jnp.sum(_xla_attention(*a) * g),
                    (0, 1, 2))(q, k, v)
    for name, a, b_ in zip(("dq", "dk", "dv"), got, want):
        np.testing.assert_allclose(a, b_, rtol=2e-3, atol=2e-3, err_msg=name)


@pytest.mark.parametrize("d,lq,lk,dtype,bq,bk", [
    # sublane-minimum head dim, default sequence-capped blocks
    (8, 256, 256, "float32", None, None),
    # the flagship native shape (d=64) as CROSS-attention with a masked
    # kv tail, bf16 — the exact dtype the bench's attnpad stage times
    (64, 256, 77, "bfloat16", None, None),
    # d=64 self-attention at the DEFAULT 512x1024 blocks the r3 attnpad
    # failure ran with (multi-block q at a padded tail)
    (64, 300, 300, "float32", 128, 256),
])
def test_flash_attention_native_d_matrix(monkeypatch, d, lq, lk, dtype,
                                         bq, bk):
    """Native sub-128 head dims across the configs attnpad/flashtune
    will run on hardware, under the FORCED 128-lane scratch layout
    (ops/flash_attention.py _FORCE_LANES — the layout where the r3
    `(128, 64) x (128, 0)` _bcast bug lived). Guards the fix so the
    next TPU window can finally record flash_native_d64_ms."""
    from flaxdiff_tpu.ops import flash_attention as fa
    monkeypatch.setattr(fa, "_FORCE_LANES", fa.LANES)
    jdt = jnp.dtype(dtype)
    key = jax.random.PRNGKey(13)
    q = jax.random.normal(key, (1, lq, 2, d), jdt)
    k = jax.random.normal(jax.random.fold_in(key, 1), (1, lk, 2, d), jdt)
    v = jax.random.normal(jax.random.fold_in(key, 2), (1, lk, 2, d), jdt)
    g = jax.random.normal(jax.random.fold_in(key, 3), (1, lq, 2, d), jdt)

    flash = lambda q_, k_, v_: flash_attention(q_, k_, v_, None, bq, bk,
                                               True)
    tol = 6e-2 if jdt == jnp.bfloat16 else 5e-3
    got = flash(q, k, v).astype(jnp.float32)
    want = _xla_attention(q, k, v).astype(jnp.float32)
    np.testing.assert_allclose(got, want, rtol=tol, atol=tol)
    gf32 = g.astype(jnp.float32)
    dq = jax.grad(lambda q_: jnp.sum(
        flash(q_, k, v).astype(jnp.float32) * gf32))(q)
    dq_ref = jax.grad(lambda q_: jnp.sum(
        _xla_attention(q_, k, v).astype(jnp.float32) * gf32))(q)
    np.testing.assert_allclose(dq.astype(jnp.float32),
                               dq_ref.astype(jnp.float32),
                               rtol=tol * 4, atol=tol * 4)


@pytest.mark.parametrize("apply_silu", [True, False])
def test_fused_groupnorm_silu_matches_xla(apply_silu):
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (2, 8, 8, 32))
    scale = jax.random.normal(jax.random.fold_in(key, 1), (32,)) * 0.1 + 1.0
    bias = jax.random.normal(jax.random.fold_in(key, 2), (32,)) * 0.1
    out_pallas = fused_groupnorm_silu(x, scale, bias, groups=8,
                                      apply_silu=apply_silu, interpret=True,
                                      force_pallas=True)
    out_ref = _xla_groupnorm_silu(x, scale, bias, 8, 1e-5, apply_silu)
    np.testing.assert_allclose(out_pallas, out_ref, rtol=1e-4, atol=1e-4)


def test_fused_groupnorm_matches_flax_groupnorm():
    import flax.linen as nn
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 4, 4, 16))
    gn = nn.GroupNorm(num_groups=4)
    params = gn.init(jax.random.PRNGKey(1), x)
    ref = jax.nn.silu(gn.apply(params, x))
    out = fused_groupnorm_silu(
        x, params["params"]["scale"], params["params"]["bias"], groups=4,
        interpret=True, force_pallas=True)
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)


def test_fused_groupnorm_multiblock_partial(monkeypatch):
    """Force nblk > 1 with a non-multiple-of-8 hw: exercises the row mask,
    per-block partial sums, and the Welford merge in the finalize."""
    import flaxdiff_tpu.ops.fused_norm as fn
    monkeypatch.setattr(fn, "_BLOCK_BYTES", 8 * 16 * 4)  # 8-row blocks
    key = jax.random.PRNGKey(7)
    x = jax.random.normal(key, (2, 10, 10, 16))  # hw=100: 13 blocks, last partial
    scale = jnp.ones((16,))
    bias = jnp.zeros((16,))
    out = fn.fused_groupnorm_silu(x, scale, bias, groups=4, interpret=True,
                                  force_pallas=True)
    ref = fn._xla_groupnorm_silu(x, scale, bias, 4, 1e-5, True)
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)


def test_fused_groupnorm_large_mean_stable(monkeypatch):
    """Large-mean activations: one-pass E[x^2]-E[x]^2 would cancel; the
    shifted per-block second moment must not."""
    import flaxdiff_tpu.ops.fused_norm as fn
    monkeypatch.setattr(fn, "_BLOCK_BYTES", 8 * 16 * 4)
    key = jax.random.PRNGKey(8)
    x = 1000.0 + jax.random.normal(key, (1, 16, 16, 16)) * 0.1
    scale = jnp.ones((16,))
    bias = jnp.zeros((16,))
    out = fn.fused_groupnorm_silu(x, scale, bias, groups=4, interpret=True,
                                  force_pallas=True)
    ref = fn._xla_groupnorm_silu(
        x.astype(jnp.float64) if jax.config.jax_enable_x64 else x,
        scale, bias, 4, 1e-5, True)
    np.testing.assert_allclose(out, ref, rtol=5e-2, atol=5e-2)


@pytest.mark.parametrize("apply_silu", [True, False])
def test_fused_groupnorm_pallas_backward_matches_xla(apply_silu):
    """The dedicated Pallas backward (r5: stats pass + finalize + dx
    pass reusing saved mean/rstd) must match XLA autodiff of the
    reference chain for dx, dscale, AND dbias."""
    key = jax.random.PRNGKey(3)
    x = jax.random.normal(key, (2, 8, 8, 32))
    scale = jax.random.normal(jax.random.fold_in(key, 1), (32,)) * 0.1 + 1.0
    bias = jax.random.normal(jax.random.fold_in(key, 2), (32,)) * 0.1

    def loss_pallas(x, s, b):
        return jnp.sum(fused_groupnorm_silu(
            x, s, b, groups=8, apply_silu=apply_silu, interpret=True,
            force_pallas=True) ** 2)

    def loss_ref(x, s, b):
        return jnp.sum(_xla_groupnorm_silu(
            x, s, b, 8, 1e-6, apply_silu) ** 2)

    g_p = jax.grad(loss_pallas, argnums=(0, 1, 2))(x, scale, bias)
    g_r = jax.grad(loss_ref, argnums=(0, 1, 2))(x, scale, bias)
    for a, b_, name in zip(g_p, g_r, ("dx", "dscale", "dbias")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-3, atol=2e-3, err_msg=name)


def test_fused_groupnorm_pallas_backward_multiblock(monkeypatch):
    """Grad correctness when hw spans multiple blocks with a partial
    tail — the backward stats pass has its own row mask + block merge."""
    import flaxdiff_tpu.ops.fused_norm as fn
    monkeypatch.setattr(fn, "_BLOCK_BYTES", 8 * 16 * 4)
    x = jax.random.normal(jax.random.PRNGKey(9), (2, 10, 10, 16))
    scale = jnp.ones((16,)) * 1.3
    bias = jnp.ones((16,)) * 0.2

    def loss(impl_env, x):
        import os
        os.environ["FLAXDIFF_FUSED_NORM_BWD"] = impl_env
        try:
            return jnp.sum(fn.fused_groupnorm_silu(
                x, scale, bias, groups=4, interpret=True,
                force_pallas=True) ** 3)
        finally:
            os.environ.pop("FLAXDIFF_FUSED_NORM_BWD", None)

    g_pallas = jax.grad(lambda x: loss("pallas", x))(x)
    g_xla = jax.grad(lambda x: loss("xla", x))(x)
    np.testing.assert_allclose(np.asarray(g_pallas), np.asarray(g_xla),
                               rtol=2e-3, atol=2e-3)


def test_full_train_step_with_interpreted_kernels(monkeypatch):
    """BOTH kernel families' REAL code paths (flash fwd+bwd, fused-norm
    fwd + the r5 Pallas backward) inside one complete train step on CPU
    via the interpret dispatch hooks — the closest CI gets to the
    on-chip sweep configuration."""
    import flaxdiff_tpu.ops.flash_attention as fa
    import numpy as np
    import optax

    from flaxdiff_tpu.models.unet import Unet
    from flaxdiff_tpu.parallel import create_mesh
    from flaxdiff_tpu.predictors import EpsilonPredictionTransform
    from flaxdiff_tpu.schedulers import CosineNoiseSchedule
    from flaxdiff_tpu.trainer import DiffusionTrainer, TrainerConfig

    monkeypatch.setenv("FLAXDIFF_FLASH_INTERPRET", "1")
    monkeypatch.setenv("FLAXDIFF_FUSED_NORM", "interpret")
    monkeypatch.setattr(fa, "_FORCE_LANES", fa.LANES)

    model = Unet(output_channels=1, emb_features=16,
                 feature_depths=(8, 12),
                 attention_configs=(None, {"heads": 2, "dim_head": 8,
                                           "backend": "flash"}),
                 num_res_blocks=1, norm_groups=4)

    def apply_fn(params, x, t, cond):
        return model.apply({"params": params}, x, t, None)

    def init_fn(key):
        return model.init(key, jnp.zeros((1, 16, 16, 1)),
                          jnp.zeros((1,)))["params"]

    trainer = DiffusionTrainer(
        apply_fn=apply_fn, init_fn=init_fn, tx=optax.adam(1e-3),
        schedule=CosineNoiseSchedule(timesteps=100),
        transform=EpsilonPredictionTransform(),
        mesh=create_mesh(axes={"data": -1}),
        config=TrainerConfig(uncond_prob=0.0, log_every=100))
    rng = np.random.default_rng(0)
    # ONE step: the interpreter compile dominates (~70 s for two steps
    # on CPU) and a second step only re-covers EMA/rng-fold paths other
    # tests already hold
    batch = {"sample": rng.standard_normal(
        (8, 16, 16, 1)).astype(np.float32)}
    loss = trainer.train_step(trainer.put_batch(batch))
    assert np.isfinite(float(jax.device_get(loss)))

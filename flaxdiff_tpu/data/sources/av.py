"""Audio/video decode layer: random AV clip sampling, audio extraction,
mel spectrograms, and sync-pair sources.

Capability parity with the reference's AV stack —
reference flaxdiff/data/sources/av_utils.py:182-589 (read_av_random_clip
family: random start frame, frame-accurate decode, audio window with
padding frames, (1, N, 1, K) framewise audio contract),
audio_utils.py:1-142 (ffmpeg audio extraction), and voxceleb2.py:159-276
(geometric face mask, "wrong" non-overlapping window for sync training,
cached mel spectrograms) — built on what this image provides: OpenCV for
frame-accurate video decode and the ffmpeg binary for audio (the
reference's decord/PyAV/moviepy backends are absent). The mel pipeline is
first-party numpy (librosa is absent).

Shapes follow the reference contract exactly:
  framewise_audio: (1, num_frames, 1, samples_per_frame)
  full_padded_audio: (num_frames + 2*padding, samples_per_frame)
  video_frames: (num_frames, H, W, 3) uint8 RGB
"""
from __future__ import annotations

import dataclasses
import functools
import os
import subprocess
import tempfile
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from .base import DataAugmenter
from .videos import VideoFolderSource

__all__ = [
    "video_fps", "video_frame_count", "video_duration",
    "extract_audio", "read_av_random_clip", "log_mel_spectrogram",
    "simple_face_mask", "AudioVideoAugmenter", "AVSyncSource",
]


# ---------------------------------------------------------------------------
# Probes
# ---------------------------------------------------------------------------

def video_fps(path: str) -> float:
    """Native frame rate (reference av_utils.py:12-16)."""
    import cv2
    cap = cv2.VideoCapture(path)
    fps = cap.get(cv2.CAP_PROP_FPS)
    cap.release()
    return float(fps) if fps and fps > 0 else 25.0


def video_frame_count(path: str) -> int:
    import cv2
    cap = cv2.VideoCapture(path)
    n = int(cap.get(cv2.CAP_PROP_FRAME_COUNT))
    cap.release()
    return n


def video_duration(path: str) -> float:
    n = video_frame_count(path)
    return n / video_fps(path)


# ---------------------------------------------------------------------------
# Audio extraction (ffmpeg subprocess -> wav -> float32 mono [-1, 1])
# ---------------------------------------------------------------------------

def _have_ffmpeg() -> bool:
    import shutil as _sh
    return _sh.which("ffmpeg") is not None


def _wav_to_float_mono(sr: int, data: np.ndarray) -> Tuple[np.ndarray, int]:
    if data.dtype == np.int16:
        audio = data.astype(np.float32) / 32768.0
    elif data.dtype == np.int32:
        audio = data.astype(np.float32) / 2147483648.0
    elif data.dtype == np.uint8:  # 8-bit PCM is unsigned with +128 offset
        audio = (data.astype(np.float32) - 128.0) / 128.0
    else:
        audio = data.astype(np.float32)
    if audio.ndim > 1:
        audio = audio.mean(axis=1)
    return audio, int(sr)


def audio_sidecar_path(video_path: str) -> str:
    """Sidecar audio convention: `<clip>.mp4` + `<clip>.wav`."""
    return os.path.splitext(video_path)[0] + ".wav"


def _extract_audio_ffmpeg(path, start_time, duration, target_sr):
    from scipy.io import wavfile
    fd, tmp_path = tempfile.mkstemp(suffix=".wav")
    os.close(fd)
    try:
        cmd = ["ffmpeg", "-y", "-loglevel", "error", "-nostdin"]
        if start_time is not None:
            cmd += ["-ss", f"{max(0.0, start_time):.6f}"]
        cmd += ["-i", path]
        if duration is not None:
            cmd += ["-t", f"{duration:.6f}"]
        cmd += ["-ac", "1", "-ar", str(target_sr), "-vn",
                "-f", "wav", tmp_path]
        subprocess.run(cmd, check=True, capture_output=True)
        return _wav_to_float_mono(*wavfile.read(tmp_path))
    finally:
        if os.path.exists(tmp_path):
            os.unlink(tmp_path)


def _extract_audio_sidecar(path, start_time, duration, target_sr):
    from scipy.io import wavfile
    from scipy.signal import resample_poly
    wav = path if path.lower().endswith(".wav") else audio_sidecar_path(path)
    if not os.path.exists(wav):
        raise FileNotFoundError(
            f"no ffmpeg binary and no sidecar audio at {wav}; provide "
            f"either ffmpeg or a `<clip>.wav` next to the video")
    sr, data = wavfile.read(wav)
    audio, sr = _wav_to_float_mono(sr, data)
    start = int(round((start_time or 0.0) * sr))
    if duration is not None:
        audio = audio[start:start + int(round(duration * sr))]
    else:
        audio = audio[start:]
    if sr != target_sr:
        from math import gcd
        g = gcd(sr, target_sr)
        audio = resample_poly(audio, target_sr // g, sr // g).astype(
            np.float32)
    return audio.astype(np.float32), target_sr


def extract_audio(path: str,
                  start_time: Optional[float] = None,
                  duration: Optional[float] = None,
                  target_sr: int = 16000) -> Tuple[np.ndarray, int]:
    """Extract mono float32 [-1, 1] audio for a media file.

    Production path shells out to ffmpeg (reference
    audio_utils.py:13-80 read_audio_ffmpeg — but the wav is parsed with
    scipy here, so the 44-byte header never leaks into the samples, a
    bug in the reference's np.fromfile read at audio_utils.py:59). When
    no ffmpeg binary exists (this image), falls back to a sidecar
    `<clip>.wav` next to the video, sliced and polyphase-resampled with
    scipy — a dependency-free capability the reference lacks."""
    if _have_ffmpeg():
        return _extract_audio_ffmpeg(path, start_time, duration, target_sr)
    return _extract_audio_sidecar(path, start_time, duration, target_sr)


# ---------------------------------------------------------------------------
# Random AV clip (the reference's core training-data primitive)
# ---------------------------------------------------------------------------

def _read_frames_at_times(path: str, times: np.ndarray,
                          native_fps: float) -> np.ndarray:
    """Frame-accurate decode of the frames nearest to `times` (seconds).

    Sequential read with index skipping — cv2 seeks are unreliable on
    some codecs, so read forward from the first wanted index instead
    (the reference's opencv reader also decodes sequentially,
    av_utils.py:59-70)."""
    import cv2
    wanted = np.round(times * native_fps).astype(int)
    first, last = int(wanted.min()), int(wanted.max())
    cap = cv2.VideoCapture(path)
    try:
        # coarse seek to just before the first wanted frame, then step
        cap.set(cv2.CAP_PROP_POS_FRAMES, first)
        pos = int(cap.get(cv2.CAP_PROP_POS_FRAMES))
        if pos != first or pos < 0:
            cap.set(cv2.CAP_PROP_POS_FRAMES, 0)
            pos = 0
        by_index: Dict[int, np.ndarray] = {}
        need = set(wanted.tolist())
        idx = pos
        while idx <= last:
            ok, frame = cap.read()
            if not ok:
                break
            if idx in need:
                by_index[idx] = cv2.cvtColor(frame, cv2.COLOR_BGR2RGB)
            idx += 1
        if not by_index:
            raise ValueError(f"no frames decoded from {path}")
        # fill any missed indices with the nearest decoded frame
        decoded = sorted(by_index)
        frames = []
        for w in wanted:
            if w in by_index:
                frames.append(by_index[w])
            else:
                nearest = min(decoded, key=lambda d: abs(d - w))
                frames.append(by_index[nearest])
        return np.stack(frames)
    finally:
        cap.release()


def read_av_random_clip(
        path: str,
        num_frames: int = 16,
        audio_frames_per_video_frame: int = 1,
        audio_frame_padding: int = 0,
        target_sr: int = 16000,
        target_fps: float = 25.0,
        rng: Optional[np.random.Generator] = None,
        random_seed: Optional[int] = None,
        retries: int = 3,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Sample a random clip of synchronized audio + video.

    Behavior parity with reference av_utils.py:read_av_random_clip
    (545-589) and its 'alt' implementation (408-545): pick a random start
    allowing `audio_frame_padding` extra audio frames on both sides,
    decode `num_frames` video frames at `target_fps`, extract the
    time-aligned audio window resampled to `target_sr` mono, pad/trim to
    exact shape, and return
    (framewise_audio [1,N,1,K], full_padded_audio [N+2P,K], frames).
    Retries with a fresh random start on decode failure (the reference
    wraps its readers in retry loops)."""
    if audio_frames_per_video_frame != 1:
        raise NotImplementedError(
            "audio_frames_per_video_frame > 1 (reference raises too, "
            "av_utils.py:537-539)")
    rng = rng or np.random.default_rng(random_seed)

    native = video_fps(path)
    total = video_frame_count(path)
    duration = total / native
    pad = int(audio_frame_padding)
    clip_dur = num_frames / target_fps
    pad_dur = pad / target_fps
    if duration < clip_dur + 2 * pad_dur:
        raise ValueError(
            f"{path}: {duration:.2f}s too short for {num_frames} frames "
            f"@ {target_fps} fps with padding {pad}")

    last_err: Optional[Exception] = None
    for _ in range(max(1, retries)):
        try:
            lo, hi = pad_dur, duration - clip_dur - pad_dur
            start_t = float(rng.uniform(lo, hi)) if hi > lo else lo
            times = start_t + np.arange(num_frames) / target_fps
            frames = _read_frames_at_times(path, times, native)

            audio_start = start_t - pad_dur
            audio_dur = clip_dur + 2 * pad_dur
            audio, _sr = extract_audio(path, start_time=audio_start,
                                       duration=audio_dur,
                                       target_sr=target_sr)
            spf = int(round(target_sr / target_fps))
            n_audio_frames = num_frames + 2 * pad
            needed = n_audio_frames * spf
            if audio.shape[0] < needed:
                audio = np.pad(audio, (0, needed - audio.shape[0]))
            full = audio[:needed].reshape(n_audio_frames, spf)
            central = full[pad:pad + num_frames]
            framewise = central.reshape(1, num_frames, 1, spf)
            return framewise, full, frames
        except Exception as e:  # decode hiccup: resample a new window
            last_err = e
    raise ValueError(f"failed to read AV clip from {path}") from last_err


# ---------------------------------------------------------------------------
# Mel spectrograms (numpy-only; reference voxceleb2.py:254-276 caches mels
# computed by an external lib — here it is first-party)
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=16)
def _mel_filterbank(sr: int, n_fft: int, n_mels: int,
                    fmin: float = 0.0,
                    fmax: Optional[float] = None) -> np.ndarray:
    """Triangular HTK-mel filterbank, [n_mels, n_fft//2 + 1]. Pure in its
    arguments and built with a Python loop, so cached — it sits in the
    per-sample dataloader hot path."""
    fmax = fmax or sr / 2.0

    def hz_to_mel(f):
        return 2595.0 * np.log10(1.0 + np.asarray(f) / 700.0)

    def mel_to_hz(m):
        return 700.0 * (10.0 ** (np.asarray(m) / 2595.0) - 1.0)

    mels = np.linspace(hz_to_mel(fmin), hz_to_mel(fmax), n_mels + 2)
    hz = mel_to_hz(mels)
    bins = np.floor((n_fft + 1) * hz / sr).astype(int)
    fb = np.zeros((n_mels, n_fft // 2 + 1), np.float32)
    for m in range(1, n_mels + 1):
        left, center, right = bins[m - 1], bins[m], bins[m + 1]
        for k in range(left, center):
            if center > left:
                fb[m - 1, k] = (k - left) / (center - left)
        for k in range(center, right):
            if right > center:
                fb[m - 1, k] = (right - k) / (right - center)
    return fb


def log_mel_spectrogram(audio: np.ndarray, sr: int = 16000,
                        n_fft: int = 512, hop: int = 160,
                        n_mels: int = 80) -> np.ndarray:
    """[T] float32 waveform -> [frames, n_mels] log-mel (numpy STFT)."""
    audio = np.asarray(audio, np.float32).reshape(-1)
    if audio.shape[0] < n_fft:
        audio = np.pad(audio, (0, n_fft - audio.shape[0]))
    n_frames = 1 + (audio.shape[0] - n_fft) // hop
    idx = (np.arange(n_fft)[None, :]
           + hop * np.arange(n_frames)[:, None])
    window = np.hanning(n_fft).astype(np.float32)
    spec = np.abs(np.fft.rfft(audio[idx] * window, axis=1)) ** 2
    mel = spec @ _mel_filterbank(sr, n_fft, n_mels).T
    return np.log10(np.maximum(mel, 1e-10)).astype(np.float32)


# ---------------------------------------------------------------------------
# Face-region mask (reference voxceleb2.py:177-203 get_simple_mask)
# ---------------------------------------------------------------------------

def simple_face_mask(size: int, face_hide_percentage: float = 0.5,
                     pad: int = 0) -> np.ndarray:
    """Geometric lower-face mask, [size, size] float32 in {0, 1}.

    Same crop-region geometry as the reference: the face box excludes
    the top-of-head/chin margins (2.36/8 of height) and side margins
    (1.8/8 of width); the mask covers the lower `face_hide_percentage`
    of that box."""
    H = W = size
    y1, y2 = 0, H - int(H * 2.36 / 8)
    x1, x2 = int(W * 1.8 / 8), W - int(W * 1.8 / 8)
    y1 = y2 - int(np.ceil(face_hide_percentage * (y2 - y1)))
    if pad:
        y1 = max(y1 - pad, 0)
        y2 = min(y2 + pad, H)
        x1 = max(x1 - pad, 0)
        x2 = min(x2 + pad, W)
    mask = np.zeros((H, W), np.float32)
    mask[y1:y2, x1:x2] = 1.0
    return mask


# ---------------------------------------------------------------------------
# Augmenter: path record -> {video, audio{...}} training batch element
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class AudioVideoAugmenter(DataAugmenter):
    """Random AV clip -> model-ready element
    (reference videos.py:156-217 AudioVideoAugmenter).

    Emits {"video": [N, S, S, 3] uint8,
           "audio": {"full_audio": [N+2P, K],
                     "framewise_audio": [1, N, 1, K]}}
    plus optional "mel" ([frames, n_mels]) and "mask" ([S, S]) channels
    (reference voxceleb2.py capabilities folded in). `audio_processor`
    is the tokenizer hook: the reference runs an AutoAudioTokenizer here;
    offline, a processor can map the waveform to any token/feature
    space."""

    num_frames: int = 16
    image_size: int = 64
    audio_frame_padding: int = 3
    target_sr: int = 16000
    target_fps: float = 25.0
    retries: int = 3
    with_mel: bool = False
    with_face_mask: bool = False
    face_hide_percentage: float = 0.5
    audio_processor: Optional[Callable[[np.ndarray], Dict[str, Any]]] = None

    def create_transform(self, **kwargs) -> Callable[..., Dict[str, Any]]:
        cfg = dataclasses.replace(self, **{k: v for k, v in kwargs.items()
                                           if hasattr(self, k)})

        def transform(record: Dict[str, Any],
                      rng: Optional[np.random.Generator] = None
                      ) -> Dict[str, Any]:
            import cv2
            rng = rng or np.random.default_rng()
            path = record["path"] if "path" in record else record["video_path"]
            framewise, full, frames = read_av_random_clip(
                path, num_frames=cfg.num_frames,
                audio_frame_padding=cfg.audio_frame_padding,
                target_sr=cfg.target_sr, target_fps=cfg.target_fps,
                rng=rng, retries=cfg.retries)
            clip = np.stack([
                cv2.resize(f, (cfg.image_size, cfg.image_size),
                           interpolation=cv2.INTER_AREA) for f in frames])
            audio: Dict[str, Any] = {
                "full_audio": full.astype(np.float32),
                "framewise_audio": framewise.astype(np.float32),
            }
            if cfg.audio_processor is not None:
                audio.update(cfg.audio_processor(full.reshape(-1)))
            out: Dict[str, Any] = {
                "video": np.ascontiguousarray(clip), "audio": audio}
            if cfg.with_mel:
                out["mel"] = log_mel_spectrogram(
                    full.reshape(-1), sr=cfg.target_sr)
            if cfg.with_face_mask:
                out["mask"] = simple_face_mask(
                    cfg.image_size, cfg.face_hide_percentage)
            for k in ("text", "identity"):
                if k in record:
                    out[k] = record[k]
            return out

        return transform


# ---------------------------------------------------------------------------
# VoxCeleb2-style sync source (reference voxceleb2.py:159-276)
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class AVSyncSource(VideoFolderSource):
    """Identity-structured AV folder (root/<identity>/.../clip.mp4).

    Each record carries the clip path + identity label. `sync_pair`
    additionally samples a "wrong" clip window that does NOT overlap the
    instance window — the negative for audio-visual sync training
    (reference voxceleb2.py:204-243 read_frames wrong-window logic)."""

    def get_source(self, path_override: Optional[str] = None):
        base = super().get_source(path_override)  # cached path gathering
        root = path_override or self.root

        class _Src:
            def __len__(self):
                return len(base)

            def __getitem__(self, i):
                rec = dict(base[i])
                rec["identity"] = os.path.relpath(
                    rec["path"], root).split(os.sep)[0]
                return rec

        return _Src()

    @staticmethod
    def sync_pair(path: str, num_frames: int,
                  rng: Optional[np.random.Generator] = None,
                  target_fps: float = 25.0,
                  target_sr: int = 16000,
                  audio_frame_padding: int = 0
                  ) -> Dict[str, np.ndarray]:
        """(true clip, non-overlapping wrong-window clip) for one video."""
        rng = rng or np.random.default_rng()
        native = video_fps(path)
        total = video_frame_count(path)
        duration = total / native
        clip_dur = num_frames / target_fps
        pad_dur = audio_frame_padding / target_fps
        if duration < 2 * clip_dur + 2 * pad_dur:
            raise ValueError(f"{path}: too short for a sync pair")

        # instance window
        lo, hi = pad_dur, duration - clip_dur - pad_dur
        start_t = float(rng.uniform(lo, hi)) if hi > lo else lo
        # wrong window: uniform over the non-overlapping remainder
        # (left of start - clip_dur, or right of start + clip_dur)
        left_hi = start_t - clip_dur
        right_lo = start_t + clip_dur
        choices = []
        if left_hi > lo:
            choices.append((lo, left_hi))
        if right_lo < hi:
            choices.append((right_lo, hi))
        if choices:
            wlo, whi = choices[int(rng.integers(len(choices)))]
            wrong_t = float(rng.uniform(wlo, whi))
        else:
            # start_t landed where neither side leaves a clip_dur gap.
            # The duration guard proves a non-overlapping pair exists when
            # the instance starts at lo, so re-anchor instead of returning
            # an overlapping (contaminated) negative.
            start_t = lo
            right_lo = start_t + clip_dur
            wrong_t = (float(rng.uniform(right_lo, hi))
                       if hi > right_lo else right_lo)
        times = start_t + np.arange(num_frames) / target_fps
        wrong_times = wrong_t + np.arange(num_frames) / target_fps
        frames = _read_frames_at_times(path, times, native)
        wrong = _read_frames_at_times(path, wrong_times, native)

        audio, _ = extract_audio(
            path, start_time=start_t - pad_dur,
            duration=clip_dur + 2 * pad_dur, target_sr=target_sr)
        spf = int(round(target_sr / target_fps))
        needed = (num_frames + 2 * audio_frame_padding) * spf
        if audio.shape[0] < needed:
            audio = np.pad(audio, (0, needed - audio.shape[0]))
        return {"frames": frames, "wrong_frames": wrong,
                "audio": audio[:needed].reshape(-1, spf),
                "start_time": np.float32(start_t),
                "wrong_start_time": np.float32(wrong_t)}

"""Measurement-driven auto-parallelism planner (ROADMAP item 3).

The closed loop the static analyzer (PR 14) and device-time attribution
(PR 19) were built for: given a model's param tree (via
`jax.eval_shape`), a pod topology, and the per-chip HBM budget
(`telemetry/memory.resolved_hbm_bytes`), the planner

  1. ENUMERATES candidate plans: every (data, fsdp, tensor) mesh-axis
     factorization of the device count, crossed with partition-rule
     tables — "generated" (an explicit `match_partition_rules` regex
     table emitted from the tree, one suffix-anchored rule per leaf) and
     "inferred" (rules=None, the TP/FSDP inference path) — plus
     pipeline-stage candidates (a "pipe" axis with a GPipe schedule,
     `parallel/pipeline.py`) where the tree has a homogeneous block
     stack the stage count divides.
  2. PRUNES statically with the PR-14 machinery: a candidate whose
     `partition_coverage` leaves an `unmatched` leaf is out (silently
     replicated HBM); a candidate whose HBM estimate — sharded params
     + optimizer moments + EMA + an activation envelope — exceeds the
     per-chip budget is out. Survivors are ranked by per-device comm
     bytes per step from the collective-inventory walker
     (`analysis/shard_rules.collective_summary`) over a comm PROXY
     program (below), converted to predicted milliseconds via the
     achieved-bandwidth calibration PR 19 writes onto registry rows
     (`comm_achieved_bytes_per_s`) when such rows are supplied — the
     ranking then trusts measured bandwidth, not raw byte counts.
  3. PROBES the top-k shortlist with short measured runs through an
     injectable `probe_fn` (the bench `plan` stage feeds the real
     `DiffusionTrainer` dispatch harness; tests feed counting mocks —
     the PR-7 autotuner mold), persisting the decision in an
     atomic-JSON cache keyed on model-shape-signature x topology x
     hardware fingerprint. A warm cache performs ZERO probes.
  4. COMMITS the decision to the program evidence registry
     (`ProgramRegistry.record` + `annotate`), so
     `scripts/compare_runs.py` / `scripts/diagnose_run.py` diff plan
     decisions across runs like any other program evidence.

Why a comm PROXY program: the planner's candidates run under jit +
sharding constraints, where GSPMD inserts the collectives AFTER the
jaxpr the walker sees — a traced FSDP train step shows zero explicit
collectives (tests/test_shard_rules.py pins this). So for each
candidate the planner traces a tiny abstract program (`jax.make_jaxpr`
with an `axis_env`, nothing compiled, no devices touched) that emits
exactly the collective traffic the plan implies — the data-axis grad
psum sized to the per-device grad shard, the ZeRO-3 fsdp all-gathers
(fwd + bwd) and grad reduce-scatter sized to the fsdp-sharded leaf
bytes, one tensor-axis psum per row-parallel site sized to the
activation envelope, and the pipeline's ppermute chain over its
M + S - 1 ticks — and feeds it to the SAME `collective_summary` byte
model that prices every other program in the registry. The estimates
are envelope-level by design; the measured probes (and PR 19's
achieved-bandwidth write-back) are what the final choice trusts.

Consumer seams: `DiffusionTrainer(plan="auto")` resolves mesh +
partition rules from here instead of the hand-written table
(`resolve_plan`), and `SamplerProgramEngine.plan_parallelism` runs the
same search with optimizer/EMA multipliers zeroed to answer the
chips-per-request vs requests-per-chip question for inference.

Metric names emitted (docs/OBSERVABILITY.md): `planner/candidates`,
`planner/pruned_unmatched`, `planner/pruned_hbm`, `planner/pruned_comm`,
`planner/probes`, `planner/cache_hits`.
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
import os
import re
from typing import (Any, Callable, Dict, List, Optional, Sequence, Tuple,
                    Union)

import numpy as np

from .mesh import AXIS_DATA, AXIS_FSDP, AXIS_TENSOR, create_mesh
from .partition import (PartitionRule, _path_str, infer_fsdp_spec,
                        infer_tp_spec, partition_coverage)

log = logging.getLogger("flaxdiff_tpu.planner")

AXIS_PIPE = "pipe"

CACHE_FILENAME = "parallel_plans.json"
CACHE_ENV = "FLAXDIFF_PLAN_CACHE"

# state multipliers for the HBM-fit estimate: adam keeps two moments
# per param, the trainer keeps one EMA copy; inference zeroes both
OPT_MULT = 2.0
EMA_MULT = 1.0
# activation envelope: bytes live at once ~ act_mult x one batch (f32).
# An envelope, not a measurement — the measured probe is the authority.
ACT_MULT = 8.0

_ITEMSIZE = 4          # proxy payloads are f32
_BLOCK_RE = re.compile(r"(^|/)block_(\d+)(/|$)")


def _block_until_ready(x) -> None:
    """The probe helpers' one host sync (the trainer's blessed-seam
    pattern — analysis/ast_rules.py HostSyncRule)."""
    import jax
    jax.block_until_ready(x)


# ---------------------------------------------------------------------------
# PartitionSpec / rule (de)serialization — the plan cache and the
# registry row must round-trip byte-stably.
# ---------------------------------------------------------------------------

def _spec_to_json(spec) -> List[Any]:
    out: List[Any] = []
    for entry in spec:
        if isinstance(entry, (tuple, list)):
            out.append([str(e) for e in entry])
        else:
            out.append(None if entry is None else str(entry))
    return out


def _spec_from_json(entries: Sequence[Any]):
    from jax.sharding import PartitionSpec
    parts = []
    for entry in entries:
        if isinstance(entry, list):
            parts.append(tuple(entry))
        else:
            parts.append(entry)
    return PartitionSpec(*parts)


def _rules_to_json(rules: Optional[Sequence[PartitionRule]]
                   ) -> Optional[List[List[Any]]]:
    if rules is None:
        return None
    return [[pattern, _spec_to_json(spec)] for pattern, spec in rules]


def _rules_from_json(data) -> Optional[List[PartitionRule]]:
    if data is None:
        return None
    return [(str(pattern), _spec_from_json(spec)) for pattern, spec in data]


# ---------------------------------------------------------------------------
# Tree introspection
# ---------------------------------------------------------------------------

def _tree_leaves(tree) -> List[Tuple[str, Tuple[int, ...], int]]:
    """(path, shape, nbytes) per leaf, sorted by path (works on arrays
    and on `jax.eval_shape` ShapeDtypeStructs alike)."""
    import jax
    out: List[Tuple[str, Tuple[int, ...], int]] = []

    def visit(path, leaf):
        shape = tuple(int(s) for s in getattr(leaf, "shape", ()))
        dtype = getattr(leaf, "dtype", None)
        itemsize = int(getattr(dtype, "itemsize", 4) or 4)
        nbytes = int(np.prod(shape, dtype=np.int64)) * itemsize \
            if shape else itemsize
        out.append((_path_str(path), shape, nbytes))
        return leaf

    jax.tree_util.tree_map_with_path(visit, tree)
    return sorted(out)


def tree_signature(tree) -> str:
    """Stable model-shape signature (the plan-cache key's first leg):
    sha1 over the sorted `path:shape:dtype` lines of the tree."""
    import jax
    items: List[str] = []

    def visit(path, leaf):
        shape = "x".join(str(int(s)) for s in getattr(leaf, "shape", ()))
        dtype = str(getattr(leaf, "dtype", "f32"))
        items.append(f"{_path_str(path)}:{shape}:{dtype}")
        return leaf

    jax.tree_util.tree_map_with_path(visit, tree)
    return hashlib.sha1("|".join(sorted(items)).encode()).hexdigest()[:12]


def _block_stack_count(paths: Sequence[str]) -> int:
    """Number of homogeneous `block_{i}` subtrees — the pipeline
    schedule's stage-divisibility input (`pipeline_blocks` requires
    n_blocks % n_stages == 0)."""
    ids = set()
    for p in paths:
        m = _BLOCK_RE.search(p)
        if m:
            ids.add(int(m.group(2)))
    return len(ids)


def generate_rules(tree, mesh, min_size: int = 2 ** 16
                   ) -> List[PartitionRule]:
    """An explicit `match_partition_rules` regex table for this tree on
    this mesh: one suffix-anchored rule per leaf (so the same table
    covers `params/...`, `ema_params/...`, and optimizer-moment copies
    of each tensor), specs from the same TP-then-FSDP inference the
    executable path uses, longest-path-first so no rule shadows a more
    specific one, closed by the catch-all `('.*', P())`.

    Every leaf matches a rule by construction, so `partition_coverage`
    reports zero `unmatched` leaves for a generated table — a big
    undividable tensor becomes an EXPLICIT replication rule instead of
    a silent one (tested for DiT, MM-DiT, and UNet trees)."""
    from jax.sharding import PartitionSpec

    rules: List[PartitionRule] = []
    for name, shape, _ in _tree_leaves(tree):
        spec = infer_tp_spec(name, shape, mesh)
        if spec is None:
            spec = infer_fsdp_spec(shape, mesh, AXIS_FSDP, min_size)
        rules.append(("(^|/)" + re.escape(name) + "$", spec))
    rules.sort(key=lambda r: len(r[0]), reverse=True)
    rules.append((".*", PartitionSpec()))
    return rules


# ---------------------------------------------------------------------------
# Candidate enumeration
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class CandidatePlan:
    """One point in the search space: an ordered mesh-axis factorization
    plus the rule-table family that shards the tree on it."""

    axes: Tuple[Tuple[str, int], ...]
    table: str                  # "generated" | "inferred" | "pipeline"
    microbatches: int = 0       # >0 only for pipeline candidates

    @property
    def axes_dict(self) -> Dict[str, int]:
        return {a: s for a, s in self.axes}

    @property
    def name(self) -> str:
        mesh = "x".join(f"{a}{s}" for a, s in self.axes)
        return f"{mesh}/{self.table}"


def enumerate_candidates(n_devices: int,
                         tree_paths: Sequence[str] = (),
                         tables: Sequence[str] = ("generated", "inferred"),
                         include_pipeline: bool = True
                         ) -> List[CandidatePlan]:
    """Every ordered (data, fsdp, tensor) factorization of the device
    count crossed with the rule-table families, plus pipeline
    candidates (data x pipe, GPipe microbatches = stages) for each
    stage count that divides both the device count and the tree's
    `block_{i}` stack."""
    out: List[CandidatePlan] = []
    divisors = [d for d in range(1, n_devices + 1) if n_devices % d == 0]
    for d in divisors:
        for f in divisors:
            if (n_devices % (d * f)) != 0:
                continue
            t = n_devices // (d * f)
            axes = ((AXIS_DATA, d), (AXIS_FSDP, f), (AXIS_TENSOR, t))
            for table in tables:
                out.append(CandidatePlan(axes=axes, table=table))
    if include_pipeline:
        blocks = _block_stack_count(tree_paths)
        for p in divisors:
            if p <= 1 or p >= n_devices + 1 or blocks == 0 \
                    or blocks % p != 0:
                continue
            out.append(CandidatePlan(
                axes=((AXIS_DATA, n_devices // p), (AXIS_PIPE, p)),
                table="pipeline", microbatches=p))
    return out


# ---------------------------------------------------------------------------
# Static evaluation: coverage, HBM fit, comm proxy
# ---------------------------------------------------------------------------

def _shard_factor(spec, sizes: Dict[str, int]) -> int:
    f = 1
    for entry in spec:
        if entry is None:
            continue
        names = entry if isinstance(entry, (tuple, list)) else (entry,)
        for a in names:
            f *= max(1, int(sizes.get(a, 1)))
    return f


def _comm_proxy_summary(sizes: Dict[str, int], *,
                        data_payload: int, fsdp_shard: int,
                        tp_payload: int, tp_sites: int,
                        pipe_payload: int, pipe_ticks: int,
                        microbatches: int) -> Dict[str, Any]:
    """Trace the candidate's implied collective traffic abstractly and
    price it with the PR-14 walker. Payloads are BYTES; the proxy is
    f32 so element counts are bytes/4 (min 1). Nothing compiles and no
    device is touched — `make_jaxpr` over ShapeDtypeStructs with an
    `axis_env` carrying the candidate's axis sizes."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from ..analysis.shard_rules import collective_summary

    d = max(1, sizes.get(AXIS_DATA, 1))
    f = max(1, sizes.get(AXIS_FSDP, 1))
    t = max(1, sizes.get(AXIS_TENSOR, 1))
    p = max(1, sizes.get(AXIS_PIPE, 1))

    def elems(nbytes: int) -> int:
        return max(1, int(nbytes) // _ITEMSIZE)

    de = elems(data_payload)
    fe = elems(fsdp_shard)
    te = elems(tp_payload)
    pe = elems(pipe_payload)

    def body(dp, fs, ff, tp, pp):
        acc = jnp.float32(0)
        if d > 1:
            # grad all-reduce over data replicas: payload = the
            # per-device grad shard (grads share the param sharding)
            acc += lax.psum(dp, AXIS_DATA).sum()
        if f > 1:
            # ZeRO-3: params gathered on use in fwd AND bwd, grads
            # reduce-scattered back to their shards
            acc += lax.all_gather(fs, AXIS_FSDP).sum()
            acc += lax.all_gather(fs, AXIS_FSDP).sum()
            acc += lax.psum_scatter(ff, AXIS_FSDP, tiled=True).sum()
        if t > 1 and tp_sites > 0:
            # one partial-sum all-reduce per row-parallel projection
            def site(c, _):
                return lax.psum(c, AXIS_TENSOR), ()
            c, _ = lax.scan(site, tp, None, length=tp_sites)
            acc += c.sum()
        if p > 1:
            # GPipe: one ring ppermute per tick over M + S - 1 ticks,
            # then the masked psum that collects stage outputs
            perm = [(i, (i + 1) % p) for i in range(p)]

            def tick(c, _):
                return lax.ppermute(c, AXIS_PIPE, perm), ()
            c, _ = lax.scan(tick, pp, None, length=max(1, pipe_ticks))
            acc += c.sum()
            acc += lax.psum(pp, AXIS_PIPE).sum() * microbatches
        return acc

    axis_env = [(a, int(s)) for a, s in sizes.items() if int(s) > 1]
    sds = jax.ShapeDtypeStruct
    closed = jax.make_jaxpr(body, axis_env=axis_env)(
        sds((de,), jnp.float32), sds((fe,), jnp.float32),
        sds((fe * f,), jnp.float32), sds((te,), jnp.float32),
        sds((pe,), jnp.float32))
    return collective_summary(closed, axis_sizes={a: int(s)
                                                  for a, s in sizes.items()})


@dataclasses.dataclass
class EvaluatedPlan:
    """One candidate after static evaluation — what pruning, ranking,
    probing, and the final decision all read."""

    candidate: CandidatePlan
    rules: Optional[List[PartitionRule]]
    unmatched: int
    hbm_estimate_bytes: int
    comm_bytes: int
    comm_bytes_by_axis: Dict[str, int]
    collectives: int
    predicted_ms: Optional[float] = None
    probe_ms: Optional[float] = None

    @property
    def name(self) -> str:
        return self.candidate.name

    @property
    def axes(self) -> Tuple[Tuple[str, int], ...]:
        return self.candidate.axes

    @property
    def axes_dict(self) -> Dict[str, int]:
        return self.candidate.axes_dict

    @property
    def microbatches(self) -> int:
        return self.candidate.microbatches


def evaluate_candidate(cand: CandidatePlan, tree, devices,
                       *, min_size: int = 2 ** 16,
                       batch_shape: Optional[Sequence[int]] = None,
                       opt_mult: float = OPT_MULT,
                       ema_mult: float = EMA_MULT,
                       act_mult: float = ACT_MULT
                       ) -> Optional[EvaluatedPlan]:
    """Static evaluation of one candidate: coverage provenance, the
    HBM-fit estimate, and the comm-proxy byte bill. None when the
    factorization cannot form a mesh over `devices`."""
    from jax.sharding import PartitionSpec  # noqa: F401 — spec types below

    sizes = cand.axes_dict
    try:
        mesh = create_mesh(axes=dict(cand.axes), devices=list(devices))
    except (ValueError, AssertionError) as e:
        log.debug("candidate %s has no mesh over %d devices: %s",
                  cand.name, len(devices), e)
        return None

    rules = (generate_rules(tree, mesh, min_size)
             if cand.table == "generated" else None)
    cov = partition_coverage(tree, mesh, rules=rules, min_size=min_size)
    unmatched = sum(1 for a in cov if a.source == "unmatched")

    # -- HBM estimate: sharded state + activation envelope ------------------
    pipe = max(1, sizes.get(AXIS_PIPE, 1))
    sharded = 0.0
    fsdp_local = 0.0
    tp_row_sites = 0
    tp_any = False
    for a in cov:
        factor = _shard_factor(a.spec, sizes)
        leaf = a.nbytes / factor
        if pipe > 1 and _BLOCK_RE.search(a.path):
            leaf /= pipe            # stage-local block stack slice
        sharded += leaf
        spec_axes = set()
        for entry in a.spec:
            if entry is None:
                continue
            spec_axes.update(entry if isinstance(entry, (tuple, list))
                             else (entry,))
        if AXIS_FSDP in spec_axes:
            fsdp_local += a.nbytes / factor
        if AXIS_TENSOR in spec_axes:
            tp_any = True
            if a.path.endswith("kernel") and _ROW_SITE.search(a.path):
                tp_row_sites += 1
    state_bytes = sharded * (1.0 + opt_mult + ema_mult)

    d = max(1, sizes.get(AXIS_DATA, 1))
    f = max(1, sizes.get(AXIS_FSDP, 1))
    t = max(1, sizes.get(AXIS_TENSOR, 1))
    total_params = sum(n for _, _, n in _tree_leaves(tree))
    if batch_shape:
        act_ref = float(np.prod(tuple(batch_shape), dtype=np.int64)) \
            * _ITEMSIZE
    else:
        # no batch known (the trainer resolves plans before it has seen
        # data): a param-scale proxy keeps the envelope > 0 and the
        # ranking deterministic
        act_ref = float(total_params)
    act_local = act_ref * act_mult / (d * f * t)
    hbm_estimate = int(state_bytes + act_local)

    # -- comm proxy ---------------------------------------------------------
    if t > 1 and tp_any and tp_row_sites == 0:
        tp_row_sites = 1            # column-only TP still pays one reduce
    microbatches = max(1, cand.microbatches or pipe)
    summary = _comm_proxy_summary(
        sizes,
        data_payload=int(sharded),
        fsdp_shard=int(fsdp_local),
        tp_payload=int(act_ref / max(1, d * f)),
        tp_sites=tp_row_sites,
        pipe_payload=int(act_ref / max(1, d * microbatches)),
        pipe_ticks=microbatches + pipe - 1,
        microbatches=1)
    return EvaluatedPlan(
        candidate=cand, rules=rules, unmatched=unmatched,
        hbm_estimate_bytes=hbm_estimate,
        comm_bytes=int(summary["comm_bytes"]),
        comm_bytes_by_axis={str(k): int(v) for k, v in
                            sorted(summary["comm_bytes_by_axis"].items())},
        collectives=int(summary["collectives"]))


_ROW_SITE = re.compile(r"(to_out|proj_out|mlp_out)/kernel$")


def achieved_bandwidth(rows: Optional[Sequence[Dict[str, Any]]]
                       ) -> Optional[float]:
    """Median `comm_achieved_bytes_per_s` over registry rows — the
    PR-19 calibration constant the ranking converts bytes to
    milliseconds with. None when no row carries a positive value
    (ranking then falls back to raw bytes, same ordering)."""
    vals: List[float] = []
    for r in rows or ():
        if not isinstance(r, dict):
            continue
        v = r.get("comm_achieved_bytes_per_s")
        try:
            v = float(v) if v is not None else None
        except (TypeError, ValueError):
            v = None
        if v and v > 0:
            vals.append(v)
    if not vals:
        return None
    return float(np.median(vals))


# ---------------------------------------------------------------------------
# The decision record
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class PlanDecision:
    """The committed output of one plan search — everything a consumer
    needs to build the mesh + rules, everything the evidence registry
    needs to diff the decision, and everything the cache needs to skip
    the next search."""

    cache_key: str
    axes: Tuple[Tuple[str, int], ...]
    table: str
    microbatches: int
    rules: Optional[List[PartitionRule]]
    comm_bytes: int
    comm_bytes_by_axis: Dict[str, int]
    collectives: int
    hbm_estimate_bytes: int
    hbm_budget_bytes: Optional[int]
    predicted_ms: Optional[float]
    probe_ms: Optional[float]
    candidates: int
    pruned_unmatched: int
    pruned_hbm: int
    pruned_comm: int
    probes: int
    cache_hit: bool
    shortlist: Tuple[str, ...]
    bandwidth_bytes_per_s: Optional[float] = None

    @property
    def name(self) -> str:
        mesh = "x".join(f"{a}{s}" for a, s in self.axes)
        return f"{mesh}/{self.table}"

    @property
    def axes_dict(self) -> Dict[str, int]:
        return {a: s for a, s in self.axes}

    @property
    def chips_per_request(self) -> int:
        """Inference reading of the plan: chips cooperating on ONE
        request = every non-data axis (ROADMAP item 1's
        chips-per-request vs requests-per-chip question)."""
        out = 1
        for a, s in self.axes:
            if a != AXIS_DATA:
                out *= int(s)
        return out

    def build_mesh(self, devices=None):
        if devices is None:
            import jax
            devices = jax.devices()
        return create_mesh(axes=dict(self.axes), devices=list(devices))

    def to_json(self) -> Dict[str, Any]:
        return {
            "cache_key": self.cache_key,
            "axes": [[a, int(s)] for a, s in self.axes],
            "table": self.table,
            "microbatches": int(self.microbatches),
            "rules": _rules_to_json(self.rules),
            "comm_bytes": int(self.comm_bytes),
            "comm_bytes_by_axis": {k: int(v) for k, v in
                                   sorted(self.comm_bytes_by_axis.items())},
            "collectives": int(self.collectives),
            "hbm_estimate_bytes": int(self.hbm_estimate_bytes),
            "hbm_budget_bytes": (int(self.hbm_budget_bytes)
                                 if self.hbm_budget_bytes else None),
            "predicted_ms": self.predicted_ms,
            "probe_ms": self.probe_ms,
            "candidates": int(self.candidates),
            "pruned_unmatched": int(self.pruned_unmatched),
            "pruned_hbm": int(self.pruned_hbm),
            "pruned_comm": int(self.pruned_comm),
            "probes": int(self.probes),
            "shortlist": list(self.shortlist),
            "bandwidth_bytes_per_s": self.bandwidth_bytes_per_s,
        }

    @classmethod
    def from_json(cls, data: Dict[str, Any],
                  cache_hit: bool = False) -> "PlanDecision":
        return cls(
            cache_key=str(data["cache_key"]),
            axes=tuple((str(a), int(s)) for a, s in data["axes"]),
            table=str(data["table"]),
            microbatches=int(data.get("microbatches", 0)),
            rules=_rules_from_json(data.get("rules")),
            comm_bytes=int(data.get("comm_bytes", 0)),
            comm_bytes_by_axis={str(k): int(v) for k, v in
                                (data.get("comm_bytes_by_axis")
                                 or {}).items()},
            collectives=int(data.get("collectives", 0)),
            hbm_estimate_bytes=int(data.get("hbm_estimate_bytes", 0)),
            hbm_budget_bytes=data.get("hbm_budget_bytes"),
            predicted_ms=data.get("predicted_ms"),
            probe_ms=data.get("probe_ms"),
            candidates=int(data.get("candidates", 0)),
            pruned_unmatched=int(data.get("pruned_unmatched", 0)),
            pruned_hbm=int(data.get("pruned_hbm", 0)),
            pruned_comm=int(data.get("pruned_comm", 0)),
            probes=int(data.get("probes", 0)),
            cache_hit=cache_hit,
            shortlist=tuple(str(s) for s in data.get("shortlist", ())),
            bandwidth_bytes_per_s=data.get("bandwidth_bytes_per_s"))


def plan_cache_key(signature: str, n_devices: int,
                   fingerprint: Optional[Dict[str, Any]] = None) -> str:
    """model-shape-signature x topology x hardware fingerprint."""
    if fingerprint is None:
        from ..telemetry.programs import hardware_fingerprint
        fingerprint = hardware_fingerprint()
    platform = str(fingerprint.get("platform", "unknown"))
    kind = str(fingerprint.get("device_kind", "") or "any")
    clean = re.sub(r"[^A-Za-z0-9_.-]+", "-", f"{platform}_{kind}")
    return f"{signature}_n{int(n_devices)}_{clean}"


# ---------------------------------------------------------------------------
# The planner
# ---------------------------------------------------------------------------

class ParallelPlanner:
    """Enumerate -> prune statically -> probe measured -> commit.

    `probe_fn(evaluated: EvaluatedPlan) -> ms` is injectable so unit
    tests can count probes with a mock (the autotuner mold —
    `self.probe_count` is the counting contract); the bench `plan`
    stage feeds the real `DiffusionTrainer` dispatch harness. A probe
    that raises simply loses (its candidate keeps only its static
    rank); when NO probe succeeds the static rank-1 survivor wins."""

    def __init__(self, cache_dir: Optional[str] = None,
                 probe_fn: Optional[Callable[[EvaluatedPlan], float]] = None,
                 top_k: int = 3,
                 metrics=None,
                 min_size: int = 2 ** 16,
                 opt_mult: float = OPT_MULT,
                 ema_mult: float = EMA_MULT,
                 act_mult: float = ACT_MULT,
                 registry_rows: Optional[Sequence[Dict[str, Any]]] = None,
                 bandwidth_bytes_per_s: Optional[float] = None):
        self.cache_dir = cache_dir
        self.probe_fn = probe_fn
        self.top_k = max(1, int(top_k))
        self.min_size = min_size
        self.opt_mult = opt_mult
        self.ema_mult = ema_mult
        self.act_mult = act_mult
        self.probe_count = 0        # total probe_fn invocations (tests)
        self._metrics = metrics
        self._plans: Dict[str, Dict[str, Any]] = {}
        self.bandwidth_bytes_per_s = (
            bandwidth_bytes_per_s
            if bandwidth_bytes_per_s is not None
            else achieved_bandwidth(registry_rows))
        if cache_dir:
            self._load()

    # -- persistence (the PR-7 atomic-JSON mold) ----------------------------
    def _cache_path(self) -> Optional[str]:
        if not self.cache_dir:
            return None
        return os.path.join(self.cache_dir, CACHE_FILENAME)

    def _load(self) -> None:
        path = self._cache_path()
        if not path or not os.path.exists(path):
            return
        try:
            with open(path) as f:
                data = json.load(f)
            plans = data.get("plans", {})
            if isinstance(plans, dict):
                self._plans.update(plans)
        except (OSError, ValueError, json.JSONDecodeError):
            # torn/corrupt cache: start fresh rather than half-trust it
            self._plans = {}

    def save(self) -> None:
        path = self._cache_path()
        if not path:
            return
        os.makedirs(self.cache_dir, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"version": 1, "plans": self._plans}, f, indent=1,
                      sort_keys=True)
        os.replace(tmp, path)       # atomic: readers never see a torn file

    def plans(self) -> Dict[str, Dict[str, Any]]:
        return dict(self._plans)

    def _count(self, name: str, n: float = 1.0) -> None:
        if self._metrics is not None:
            try:
                self._metrics.counter(name).inc(n)
            except Exception as e:  # noqa: BLE001 — metrics never gate
                log.debug("planner metric %s failed: %s", name, e)

    # -- the search ---------------------------------------------------------
    def plan(self, tree, *, devices=None,
             batch_shape: Optional[Sequence[int]] = None,
             hbm_bytes: Optional[float] = None,
             tables: Sequence[str] = ("generated", "inferred"),
             include_pipeline: bool = True) -> PlanDecision:
        """Search a plan for `tree` over `devices`.

        `hbm_bytes` is the per-chip budget; None resolves it via
        `telemetry.memory.resolved_hbm_bytes` (the FLAXDIFF_HBM_BYTES
        env override first, then allocator stats) and skips HBM
        pruning entirely when neither source exists."""
        import jax
        if devices is None:
            devices = jax.devices()
        n = len(devices)
        if hbm_bytes is None:
            from ..telemetry.memory import resolved_hbm_bytes
            hbm_bytes = resolved_hbm_bytes()

        signature = tree_signature(tree)
        key = plan_cache_key(signature, n)
        cached = self._plans.get(key)
        if cached is not None:
            self._count("planner/cache_hits")
            log.info("plan cache hit %s -> %s", key, cached.get("table"))
            return PlanDecision.from_json(cached, cache_hit=True)

        paths = [p for p, _, _ in _tree_leaves(tree)]
        cands = enumerate_candidates(n, tree_paths=paths, tables=tables,
                                     include_pipeline=include_pipeline)
        evals: List[EvaluatedPlan] = []
        for cand in cands:
            ev = evaluate_candidate(
                cand, tree, devices, min_size=self.min_size,
                batch_shape=batch_shape, opt_mult=self.opt_mult,
                ema_mult=self.ema_mult, act_mult=self.act_mult)
            if ev is not None:
                evals.append(ev)
        self._count("planner/candidates", len(evals))

        matched = [e for e in evals if e.unmatched == 0]
        pruned_unmatched = len(evals) - len(matched)
        self._count("planner/pruned_unmatched", pruned_unmatched)

        if hbm_bytes:
            fit = [e for e in matched
                   if e.hbm_estimate_bytes <= float(hbm_bytes)]
        else:
            fit = list(matched)
        pruned_hbm = len(matched) - len(fit)
        self._count("planner/pruned_hbm", pruned_hbm)
        if not fit:
            raise ValueError(
                f"no candidate plan fits: {len(evals)} enumerated, "
                f"{pruned_unmatched} unmatched, {pruned_hbm} over the "
                f"{hbm_bytes} byte HBM budget")

        bw = self.bandwidth_bytes_per_s
        for e in fit:
            if bw:
                e.predicted_ms = e.comm_bytes / bw * 1e3
        # stable comm ranking; name tie-break keeps the order (and the
        # committed evidence row) deterministic across runs
        fit.sort(key=lambda e: (e.comm_bytes, e.name))
        shortlist = fit[:self.top_k]
        pruned_comm = len(fit) - len(shortlist)
        self._count("planner/pruned_comm", pruned_comm)

        probes = 0
        if self.probe_fn is not None and len(shortlist) > 1:
            for e in shortlist:
                self.probe_count += 1
                probes += 1
                try:
                    e.probe_ms = float(self.probe_fn(e))
                except Exception as err:  # noqa: BLE001 — a failing
                    # candidate is just not chosen; keep the cause
                    log.warning("plan probe %s failed: %r", e.name, err)
                    e.probe_ms = None
            self._count("planner/probes", probes)
        measured = [e for e in shortlist if e.probe_ms is not None]
        chosen = (min(measured, key=lambda e: (e.probe_ms, e.name))
                  if measured else shortlist[0])

        decision = PlanDecision(
            cache_key=key,
            axes=chosen.axes, table=chosen.candidate.table,
            microbatches=chosen.microbatches, rules=chosen.rules,
            comm_bytes=chosen.comm_bytes,
            comm_bytes_by_axis=chosen.comm_bytes_by_axis,
            collectives=chosen.collectives,
            hbm_estimate_bytes=chosen.hbm_estimate_bytes,
            hbm_budget_bytes=int(hbm_bytes) if hbm_bytes else None,
            predicted_ms=chosen.predicted_ms, probe_ms=chosen.probe_ms,
            candidates=len(evals), pruned_unmatched=pruned_unmatched,
            pruned_hbm=pruned_hbm, pruned_comm=pruned_comm,
            probes=probes, cache_hit=False,
            shortlist=tuple(e.name for e in shortlist),
            bandwidth_bytes_per_s=bw)
        self._plans[key] = decision.to_json()
        self.save()
        log.info("plan %s: %d candidates, pruned %d unmatched / %d hbm "
                 "/ %d comm, %d probes -> %s (%d comm bytes)", key,
                 decision.candidates, pruned_unmatched, pruned_hbm,
                 pruned_comm, probes, decision.name, decision.comm_bytes)
        return decision

    # -- evidence -----------------------------------------------------------
    def commit(self, registry, decision: PlanDecision,
               kind: str = "plan") -> Optional[Dict[str, Any]]:
        """Land the decision in the program evidence registry: one
        byte-stable `record` row with the static fields, then the
        measured fields through the `annotate` write-back channel (the
        devprof mold) — re-planning on a warm cache re-annotates the
        same row instead of minting a new one."""
        if registry is None:
            return None
        registry.record(
            kind, decision.cache_key,
            collectives=decision.collectives,
            comm_bytes_by_axis=decision.comm_bytes_by_axis,
            extra={
                "plan": decision.name,
                "plan_axes": {a: int(s) for a, s in decision.axes},
                "plan_table": decision.table,
                "plan_microbatches": int(decision.microbatches),
                "plan_candidates": int(decision.candidates),
                "plan_pruned_unmatched": int(decision.pruned_unmatched),
                "plan_pruned_hbm": int(decision.pruned_hbm),
                "plan_pruned_comm": int(decision.pruned_comm),
                "plan_shortlist": list(decision.shortlist),
                "plan_hbm_estimate_bytes": int(decision.hbm_estimate_bytes),
                "plan_hbm_budget_bytes": (
                    int(decision.hbm_budget_bytes)
                    if decision.hbm_budget_bytes else None),
            })
        fields: Dict[str, Any] = {
            "plan_chosen": decision.name,
            "plan_probes": int(decision.probes),
            "plan_cache_hit": int(decision.cache_hit),
        }
        if decision.predicted_ms is not None:
            fields["plan_predicted_ms"] = float(decision.predicted_ms)
        if decision.probe_ms is not None:
            fields["plan_probe_ms"] = float(decision.probe_ms)
        return registry.annotate(kind, decision.cache_key, fields)


def resolve_plan(plan: Union[str, PlanDecision], tree, *,
                 devices=None, telemetry=None, kind: str = "plan",
                 planner: Optional[ParallelPlanner] = None,
                 **plan_kwargs) -> PlanDecision:
    """The consumer seam: `"auto"` runs a static search (cache dir from
    $FLAXDIFF_PLAN_CACHE; no probes — measured probing is the bench
    `plan` stage's job), a `PlanDecision` passes through. Either way
    the decision is committed to `telemetry.programs` when the hub
    carries a registry."""
    if isinstance(plan, PlanDecision):
        decision = plan
        committer = planner or ParallelPlanner(metrics=_hub_metrics(telemetry))
    elif plan == "auto":
        if planner is None:
            planner = ParallelPlanner(
                cache_dir=os.environ.get(CACHE_ENV) or None,
                metrics=_hub_metrics(telemetry))
        # consumers execute plain jit train/sample steps, which cannot
        # run a GPipe schedule — pipeline candidates are for the
        # explicit `pipelined_dit_apply` path only
        plan_kwargs.setdefault("include_pipeline", False)
        decision = planner.plan(tree, devices=devices, **plan_kwargs)
        committer = planner
    else:
        raise ValueError(f"plan must be 'auto' or a PlanDecision, "
                         f"got {plan!r}")
    registry = getattr(telemetry, "programs", None)
    if registry is not None:
        committer.commit(registry, decision, kind=kind)
    return decision


def _hub_metrics(telemetry):
    """A Telemetry hub doubles as the metrics sink when it exposes
    `counter` (it does — the serving engine counts on it directly)."""
    return telemetry if hasattr(telemetry, "counter") else None

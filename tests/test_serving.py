"""Serving subsystem tests (flaxdiff_tpu/serving/, docs/SERVING.md).

Scheduler mechanics run against a jax-free FakeEngine (fast,
deterministic); the host-sync contract is enforced with counting mocks
on the module-level seams (the PR-5 convention); the acceptance bars —
batched == solo bit-identity under padding/masking/chunking, and a
warm program cache that never re-traces — run against a real tiny
pipeline.
"""
import threading
import time

import numpy as np
import pytest

from flaxdiff_tpu.serving import (DeadlineExceeded, PoissonWorkloadSpec,
                                  RequestState, SampleRequest,
                                  SchedulerClosed, SchedulerConfig,
                                  ServingScheduler, build_workload,
                                  bucket_up, nfe_bucket, replay)
from flaxdiff_tpu.serving import scheduler as sched_mod
from flaxdiff_tpu.telemetry import Telemetry


# ---------------------------------------------------------------------------
# Pure helpers
# ---------------------------------------------------------------------------

def test_bucket_helpers():
    assert bucket_up(1, (1, 2, 4)) == 1
    assert bucket_up(3, (1, 2, 4)) == 4
    assert bucket_up(9, (1, 2, 4)) == 4      # capped at max bucket
    assert nfe_bucket(1) == 1
    assert nfe_bucket(5) == 8
    assert nfe_bucket(64) == 64


def test_request_validation():
    with pytest.raises(ValueError, match="diffusion_steps"):
        SampleRequest(diffusion_steps=0)
    r = SampleRequest(prompts=["a", "b", "c"])
    assert r.num_samples == 3                # prompts drive the block


def test_poisson_workload_deterministic():
    spec = PoissonWorkloadSpec(
        n_requests=16, rate_hz=8.0, seed=99,
        mix=[{"resolution": 8, "diffusion_steps": 4},
             {"resolution": 8, "diffusion_steps": 8}])
    w1, w2 = build_workload(spec), build_workload(spec)
    assert [t for t, _ in w1] == [t for t, _ in w2]
    assert [r.seed for _, r in w1] == [r.seed for _, r in w2]
    assert [r.diffusion_steps for _, r in w1] \
        == [r.diffusion_steps for _, r in w2]
    # arrivals strictly increase; both NFEs drawn
    ts = [t for t, _ in w1]
    assert all(b > a for a, b in zip(ts, ts[1:]))
    assert {r.diffusion_steps for _, r in w1} == {4, 8}
    # a different seed is a different workload
    assert [t for t, _ in build_workload(
        PoissonWorkloadSpec(n_requests=16, rate_hz=8.0, seed=100,
                            mix=spec.mix))] != ts


# ---------------------------------------------------------------------------
# FakeEngine: the scheduler's engine contract without jax
# ---------------------------------------------------------------------------

class FakeEngine:
    """Deterministic jax-free engine: result rows are f(seed); advance
    moves each row min(remaining, round_steps); per-call counters let
    tests assert what compute was (not) spent."""

    def __init__(self, step_delay_s: float = 0.0):
        self.prepared = []
        self.advance_calls = []
        self.finalize_calls = []
        self.step_delay_s = step_delay_s
        self.telemetry = Telemetry(enabled=False)

    def group_key(self, req):
        return (req.resolution, req.sampler, req.num_samples)

    def prepare(self, req, future, submit_t, admit_t):
        st = RequestState(req=req, future=future, submit_t=submit_t,
                          admit_t=admit_t, group=self.group_key(req),
                          x=None, rng=None, state=None, pairs=None,
                          terminal_t=0.0, cond=None, uncond=None)
        self.prepared.append(req)
        return st

    def advance(self, rows, bucket, round_steps):
        self.advance_calls.append((len(rows), bucket, round_steps))
        if self.step_delay_s:
            time.sleep(self.step_delay_s)
        finished = []
        for r in rows:
            r.done += min(r.remaining, round_steps)
            r.rounds += 1
            if r.remaining <= 0:
                finished.append(r)
        return finished, 0.0

    def finalize(self, rows, bucket):
        self.finalize_calls.append((len(rows), bucket))
        out = np.stack([np.full((r.req.num_samples, 2, 2, 1),
                                float(r.req.seed)) for r in rows])
        return out, 0.0


def _fake_scheduler(tel=None, **cfg_kwargs):
    eng = FakeEngine()
    tel = tel or Telemetry(enabled=False)
    cfg = SchedulerConfig(**{"round_steps": 4,
                             "batch_buckets": (1, 2, 4), **cfg_kwargs})
    return eng, ServingScheduler(engine=eng, config=cfg, telemetry=tel,
                                 autostart=False)


def test_scheduler_completes_all_and_routes_results():
    tel = Telemetry(enabled=False)
    eng, sched = _fake_scheduler(tel)
    reqs = [SampleRequest(resolution=8, diffusion_steps=3 + (i % 3),
                          sampler=("ddim", "euler")[i % 2], seed=100 + i)
            for i in range(10)]
    futs = [sched.submit(r) for r in reqs]
    sched.start()
    outs = [f.result(timeout=10) for f in futs]
    sched.close()
    for r, o in zip(reqs, outs):
        # each request got ITS OWN rows back, whatever it batched with
        assert np.all(o.samples == float(r.seed))
        assert o.samples.shape == (1, 2, 2, 1)
        assert o.rounds >= 1 and o.latency_ms >= o.queue_ms
    snap = tel.registry.snapshot()
    assert snap["serving/requests_in"] == 10
    assert snap["serving/requests_ok"] == 10
    assert snap.get("serving/shed", 0) == 0
    # two groups of 5 bucketed to 4+1 rows -> some padding happened
    assert snap["serving/rows_real"] >= 10


def test_heterogeneous_nfe_exits_early():
    """A short request grouped with a long one completes in fewer
    rounds — continuous admission, not wait-for-longest."""
    eng, sched = _fake_scheduler(round_steps=2)
    short = sched.submit(SampleRequest(resolution=8, diffusion_steps=2,
                                       sampler="ddim", seed=1))
    long = sched.submit(SampleRequest(resolution=8, diffusion_steps=8,
                                      sampler="ddim", seed=2))
    sched.start()
    r_short = short.result(timeout=10)
    r_long = long.result(timeout=10)
    sched.close()
    assert r_short.rounds == 1 and r_long.rounds == 4
    # both rode the same first round (one group)
    assert eng.advance_calls[0][0] == 2


def test_deadline_shed_before_compute():
    eng, sched = _fake_scheduler()
    tel = sched.telemetry
    doomed = sched.submit(SampleRequest(resolution=8, diffusion_steps=4,
                                        deadline_s=0.0))
    time.sleep(0.01)                          # deadline passes in-queue
    ok = sched.submit(SampleRequest(resolution=8, diffusion_steps=4,
                                    seed=5))
    sched.start()
    assert np.all(ok.result(timeout=10).samples == 5.0)
    with pytest.raises(DeadlineExceeded):
        doomed.result(timeout=10)
    sched.close()
    # the shed request never reached prepare/advance
    assert all(r.deadline_s is None for r in eng.prepared)
    assert tel.registry.counter("serving/shed").value == 1


def test_queue_full_sheds_at_the_door():
    eng, sched = _fake_scheduler(max_queue=1)
    keep = sched.submit(SampleRequest(resolution=8, diffusion_steps=2))
    reject = sched.submit(SampleRequest(resolution=8, diffusion_steps=2))
    with pytest.raises(DeadlineExceeded, match="queue full"):
        reject.result(timeout=1)
    sched.start()
    keep.result(timeout=10)
    sched.close()
    assert sched.telemetry.registry.counter("serving/shed").value == 1


def test_midflight_deadline_shed_at_round_boundary():
    """A request whose deadline passes BETWEEN rounds is shed at the
    next round boundary (not only at dispatch admission), with
    `serving/shed_midflight` counting it and the future resolving
    `DeadlineExceeded` — no more compute is spent on it."""
    eng = FakeEngine(step_delay_s=0.03)
    tel = Telemetry(enabled=False)
    sched = ServingScheduler(
        engine=eng, telemetry=tel, autostart=False,
        config=SchedulerConfig(round_steps=1, batch_buckets=(1, 2)))
    # 8 rounds x 30 ms but a 50 ms budget: admitted (deadline alive at
    # dispatch), then expires mid-flight
    doomed = sched.submit(SampleRequest(resolution=8, diffusion_steps=8,
                                        sampler="ddim", deadline_s=0.05))
    ok = sched.submit(SampleRequest(resolution=8, diffusion_steps=8,
                                    sampler="ddim", seed=9))
    sched.start()
    assert np.all(ok.result(timeout=20).samples == 9.0)
    with pytest.raises(DeadlineExceeded, match="mid-flight"):
        doomed.result(timeout=20)
    sched.close()
    snap = tel.registry.snapshot()
    assert snap["serving/shed_midflight"] == 1
    assert snap["serving/shed"] == 1
    # it WAS admitted (this is the mid-flight case, not queue shedding)
    assert any(r.deadline_s is not None for r in eng.prepared)


def test_dispatch_thread_death_fails_all_futures(monkeypatch):
    """Regression for the stranded-future bug class: if the dispatch
    thread dies, every queued/in-flight future must resolve with a
    typed ServingFault, and later submits are refused — nobody waits
    forever."""
    from flaxdiff_tpu.serving import ServingFault
    eng, sched = _fake_scheduler()
    futs = [sched.submit(SampleRequest(resolution=8, diffusion_steps=4,
                                       seed=i)) for i in range(3)]
    monkeypatch.setattr(
        sched, "_pick_group_locked",
        lambda: (_ for _ in ()).throw(RuntimeError("scheduler bug")))
    sched.start()
    for f in futs:
        with pytest.raises(ServingFault) as ei:
            f.result(timeout=10)
        assert ei.value.kind == "scheduler_died"
    with pytest.raises(SchedulerClosed):
        sched.submit(SampleRequest(resolution=8)).result(timeout=5)
    sched.close(drain=False)


def test_submit_after_close_and_drain():
    eng, sched = _fake_scheduler()
    futs = [sched.submit(SampleRequest(resolution=8, diffusion_steps=4,
                                       seed=i)) for i in range(3)]
    sched.start()
    sched.close(drain=True)                  # drain finishes queued work
    for f in futs:
        assert f.result(timeout=1) is not None
    with pytest.raises(SchedulerClosed):
        sched.submit(SampleRequest(resolution=8)).result(timeout=1)


def test_close_without_drain_cancels():
    eng, sched = _fake_scheduler()
    futs = [sched.submit(SampleRequest(resolution=8, diffusion_steps=4))
            for _ in range(4)]
    sched.close(drain=False)                 # never started: all cancel
    sched.start()
    for f in futs:
        with pytest.raises(SchedulerClosed):
            f.result(timeout=1)


def test_completion_sync_seams_counted(monkeypatch):
    """The PR-5 counting-mock contract: ALL host syncs go through the
    module seams, and one completed batch costs exactly one
    block_until_ready + one device_get — the dispatch loop itself
    never syncs."""
    blocks, gets = [], []
    real_block = sched_mod._block_until_ready
    real_get = sched_mod._device_get
    monkeypatch.setattr(sched_mod, "_block_until_ready",
                        lambda x: (blocks.append(1), real_block(x))[1])
    monkeypatch.setattr(sched_mod, "_device_get",
                        lambda x: (gets.append(1), real_get(x))[1])
    eng, sched = _fake_scheduler(round_steps=16)
    futs = [sched.submit(SampleRequest(resolution=8, diffusion_steps=4,
                                       sampler="ddim", seed=i))
            for i in range(3)]               # one group, one round
    sched.start()
    for f in futs:
        f.result(timeout=10)
    sched.close()
    assert len(blocks) == 1 and len(gets) == 1


def test_backpressure_bounds_inflight(monkeypatch):
    """With a stalled completion thread the dispatch loop must WAIT
    (counted), not queue unbounded completed batches."""
    real_block = sched_mod._block_until_ready

    def slow_block(x):
        time.sleep(0.05)
        return real_block(x)

    monkeypatch.setattr(sched_mod, "_block_until_ready", slow_block)
    tel = Telemetry(enabled=False)
    eng = FakeEngine()
    sched = ServingScheduler(
        engine=eng, telemetry=tel, autostart=False,
        config=SchedulerConfig(round_steps=8, batch_buckets=(1,),
                               max_inflight=1))
    futs = [sched.submit(SampleRequest(resolution=8, diffusion_steps=4,
                                       seed=i)) for i in range(6)]
    sched.start()
    for f in futs:
        f.result(timeout=20)
    sched.close()
    assert tel.registry.counter("serving/backpressure_waits").value > 0
    snap = tel.registry.snapshot()
    assert snap["serving/requests_ok"] == 6


def test_replay_with_fake_engine():
    eng, sched = _fake_scheduler()
    sched.start()
    spec = PoissonWorkloadSpec(
        n_requests=12, rate_hz=200.0, seed=3,
        mix=[{"resolution": 8, "diffusion_steps": 4},
             {"resolution": 8, "diffusion_steps": 8}])
    summary = replay(sched, build_workload(spec), timeout_s=20)
    sched.close()
    assert summary["completed"] == 12 and summary["shed"] == 0
    assert summary["latency_ms"]["p50"] is not None
    assert summary["latency_ms"]["p99"] >= summary["latency_ms"]["p50"]
    assert summary["throughput_rps"] > 0


def test_thread_safe_submit():
    eng, sched = _fake_scheduler(max_queue=512)
    sched.start()
    futs, lock = [], threading.Lock()

    def blast(base):
        mine = [sched.submit(SampleRequest(resolution=8,
                                           diffusion_steps=4,
                                           seed=base + i))
                for i in range(20)]
        with lock:
            futs.extend(mine)

    threads = [threading.Thread(target=blast, args=(1000 * t,))
               for t in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    results = [f.result(timeout=20) for f in futs]
    sched.close()
    assert len(results) == 80
    assert {float(r.samples.flat[0]) for r in results} \
        == {float(r.request.seed) for r in results}


# ---------------------------------------------------------------------------
# Real-engine acceptance: bit-identity + warm cache
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def tiny_pipe():
    import jax
    import jax.numpy as jnp

    from flaxdiff_tpu.inference import (DiffusionInferencePipeline,
                                        build_model)
    config = {
        "model": {"name": "simple_dit", "emb_features": 32,
                  "num_heads": 4, "num_layers": 1, "patch_size": 4,
                  "output_channels": 1},
        "schedule": {"name": "cosine", "timesteps": 100},
        "predictor": "epsilon",
    }
    model = build_model("simple_dit", emb_features=32, num_heads=4,
                        num_layers=1, patch_size=4, output_channels=1)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8, 8, 1)),
                        jnp.zeros((1,)), None)
    return DiffusionInferencePipeline.from_config(config, params=params)


def test_batched_bit_identity_with_padding_and_chunking(tiny_pipe):
    """THE acceptance bar: requests batched, padded (buckets force a
    padding row), NFE-masked, and chunked across rounds produce
    bit-identical samples to solo generate_samples with the same
    seed — including a stochastic sampler's per-step noise."""
    tel = Telemetry(enabled=False)
    sched = ServingScheduler(
        pipeline=tiny_pipe, telemetry=tel, autostart=False,
        config=SchedulerConfig(round_steps=2, batch_buckets=(4,)))
    reqs = [
        SampleRequest(resolution=8, channels=1, diffusion_steps=3,
                      sampler="euler_ancestral", seed=7, use_ema=False),
        SampleRequest(resolution=8, channels=1, diffusion_steps=5,
                      sampler="euler_ancestral", seed=11, use_ema=False),
        SampleRequest(resolution=8, channels=1, diffusion_steps=4,
                      sampler="ddim", seed=3, use_ema=False),
    ]
    futs = [sched.submit(r) for r in reqs]
    sched.start()
    outs = [f.result(timeout=300) for f in futs]
    sched.close()

    for r, o in zip(reqs, outs):
        solo = tiny_pipe.generate_samples(
            num_samples=1, resolution=8, channels=1,
            diffusion_steps=r.diffusion_steps, sampler=r.sampler,
            seed=r.seed, use_ema=False)
        np.testing.assert_array_equal(o.samples, solo)
    snap = tel.registry.snapshot()
    # buckets=(4,) with groups of 2 and 1 -> padding rows existed, and
    # the padded outputs were still bit-exact above
    assert snap["serving/rows_padded"] > 0
    assert snap["serving/requests_ok"] == 3


def test_multistep_state_carry_bit_identity(tiny_pipe):
    """Multistep DPM is the hardest carry: its scan state (denoised
    history + lambda trail, keyed on the global step index) must
    survive chunk boundaries, masking, and batch stacking bit-exactly."""
    sched = ServingScheduler(
        pipeline=tiny_pipe, telemetry=Telemetry(enabled=False),
        autostart=False,
        config=SchedulerConfig(round_steps=2, batch_buckets=(1, 2)))
    reqs = [SampleRequest(resolution=8, channels=1, diffusion_steps=5,
                          sampler="multistep_dpm", seed=13,
                          use_ema=False),
            SampleRequest(resolution=8, channels=1, diffusion_steps=3,
                          sampler="multistep_dpm", seed=17,
                          use_ema=False)]
    futs = [sched.submit(r) for r in reqs]
    sched.start()
    outs = [f.result(timeout=300) for f in futs]
    sched.close()
    for r, o in zip(reqs, outs):
        solo = tiny_pipe.generate_samples(
            num_samples=1, resolution=8, channels=1,
            diffusion_steps=r.diffusion_steps, sampler=r.sampler,
            seed=r.seed, use_ema=False)
        np.testing.assert_array_equal(o.samples, solo)


def test_warm_cache_never_retraces(tiny_pipe):
    """Repeat traffic of identical request shapes must be served
    entirely from the compiled-program cache: zero misses on the
    second pass (the bench stage asserts the same end to end)."""
    tel = Telemetry(enabled=False)
    sched = ServingScheduler(
        pipeline=tiny_pipe, telemetry=tel, autostart=False,
        config=SchedulerConfig(round_steps=2, batch_buckets=(1, 2)))

    def pass_once():
        futs = [sched.submit(SampleRequest(
            resolution=8, channels=1, diffusion_steps=n, sampler="ddim",
            seed=s, use_ema=False))
            for n, s in ((3, 1), (3, 2), (5, 9))]
        sched.start()
        return [f.result(timeout=300) for f in futs]

    first = pass_once()
    misses_cold = tel.registry.counter(
        "serving/program_cache_misses").value
    assert misses_cold > 0
    second = pass_once()
    sched.close()
    assert tel.registry.counter(
        "serving/program_cache_misses").value == misses_cold
    assert tel.registry.counter("serving/program_cache_hits").value > 0
    # same request, same seed -> same samples on both passes
    for a, b in zip(first, second):
        np.testing.assert_array_equal(a.samples, b.samples)


def test_prompted_cfg_bit_identity():
    """Conditioned + CFG requests through the scheduler match solo
    prompted generation bitwise (cond/uncond row stacking is
    output-invariant)."""
    import jax
    import jax.numpy as jnp

    from flaxdiff_tpu.inference import (DiffusionInferencePipeline,
                                        build_model)
    from flaxdiff_tpu.inputs import (ConditionalInputConfig,
                                     DiffusionInputConfig)
    from flaxdiff_tpu.inputs.encoders import HashTextEncoder

    enc = HashTextEncoder.create(features=16, max_length=8)
    model = build_model("simple_dit", emb_features=32, num_heads=4,
                        num_layers=1, patch_size=4, output_channels=1)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8, 8, 1)),
                        jnp.zeros((1,)), jnp.asarray(enc([""])))
    pipe = DiffusionInferencePipeline.from_config(
        {"model": {"name": "simple_dit", "emb_features": 32,
                   "num_heads": 4, "num_layers": 1, "patch_size": 4,
                   "output_channels": 1},
         "schedule": {"name": "cosine", "timesteps": 100},
         "predictor": "epsilon"}, params=params)
    pipe.input_config = DiffusionInputConfig(
        sample_data_key="sample", sample_data_shape=(8, 8, 1),
        conditions=[ConditionalInputConfig(encoder=enc)])

    sched = ServingScheduler(
        pipeline=pipe, telemetry=Telemetry(enabled=False),
        autostart=False,
        config=SchedulerConfig(round_steps=2, batch_buckets=(1, 2)))
    futs = [sched.submit(SampleRequest(
        resolution=8, channels=1, diffusion_steps=3, sampler="ddim",
        guidance_scale=2.0, prompts=[p], seed=s, use_ema=False))
        for p, s in (("a red flower", 21), ("blue sky", 22))]
    sched.start()
    outs = [f.result(timeout=300) for f in futs]
    sched.close()
    for (p, s), o in zip((("a red flower", 21), ("blue sky", 22)), outs):
        solo = pipe.generate_samples(
            prompts=[p], resolution=8, channels=1, diffusion_steps=3,
            sampler="ddim", guidance_scale=2.0, seed=s, use_ema=False)
        np.testing.assert_array_equal(o.samples, solo)

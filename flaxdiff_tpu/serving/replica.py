"""Replica: one health-tracked `ServingScheduler` + `EngineSupervisor`
unit inside a `ReplicaPool` (docs/SERVING.md "Front door").

PR 15 made a single scheduler survivable; this layer treats the WHOLE
scheduler as the unit of failure. A `Replica` wraps one scheduler and
derives a four-state health signal the front door routes on:

    HEALTHY     supervisor SERVING, fault-rate EWMA low, queue shallow
    DEGRADED    fault-rate EWMA above threshold, or queue pressure
                beyond the degraded fraction of max_queue — routable,
                but only when no HEALTHY replica is
    REBUILDING  supervisor mid DRAINING/REBUILDING (device loss is
                being repaired) — routable as a last resort; submits
                queue and serve once the rebuild lands
    DEAD        scheduler closed (explicitly, by a thread-death sweep,
                or by `kill()` — the `serving.replica_lost` chaos
                site). Never routed; the door fails its in-flight
                requests over to survivors.

Everything here is host-side bookkeeping: no jax imports, no device
work — the host-sync lint budget for this file is pinned at zero
(analysis/budgets.py).
"""
from __future__ import annotations

import dataclasses
import threading
from typing import Optional

from ..resilience.events import record_event
from .supervision import SERVING as _SUP_SERVING

# health states, ordered by routing preference (lower routes first);
# exported as the `frontdoor/replica_health/<replica>` gauge values
HEALTHY, DEGRADED, REBUILDING, DEAD = ("healthy", "degraded",
                                       "rebuilding", "dead")
HEALTH_RANK = {HEALTHY: 0, DEGRADED: 1, REBUILDING: 2, DEAD: 3}


@dataclasses.dataclass(frozen=True)
class ReplicaHealthConfig:
    """Thresholds for the DEGRADED derivation.

    ewma_alpha: weight of the newest outcome in the fault-rate EWMA
      (outcome stream: 1.0 per terminal fault / failover the door
      observed on this replica, 0.0 per completed result).
    ewma_degraded: EWMA at or above this marks the replica DEGRADED.
    queue_degraded_frac: queued fraction of the scheduler's max_queue
      at or above which the replica is DEGRADED (back-pressure routing
      kicks in well before the replica itself starts shedding).
    """
    ewma_alpha: float = 0.25
    ewma_degraded: float = 0.5
    queue_degraded_frac: float = 0.75


class Replica:
    """One named scheduler behind the front door.

    The replica does not own a thread: health is derived on read from
    supervisor state + the outcome EWMA + queue depth, all host-side
    accessors. `kill()` is the replica-loss path (chaos or operator):
    it marks the replica DEAD immediately — routing skips it from that
    instant — and closes the scheduler non-draining in the background
    so in-flight futures resolve (`SchedulerClosed`) and the door can
    fail them over without waiting for the close to finish joining.
    """

    def __init__(self, name: str, scheduler,
                 config: Optional[ReplicaHealthConfig] = None):
        self.name = name
        self.scheduler = scheduler
        self.config = config or ReplicaHealthConfig()
        self._lock = threading.Lock()
        self._ewma = 0.0
        self._dead = False
        self._kill_thread: Optional[threading.Thread] = None

    # -- health ---------------------------------------------------------------
    def note_outcome(self, ok: bool) -> None:
        """Feed one observed terminal outcome (door-side) into the
        fault-rate EWMA: False for a fault/failover attributed to this
        replica, True for a delivered result."""
        a = self.config.ewma_alpha
        with self._lock:
            self._ewma = a * (0.0 if ok else 1.0) + (1 - a) * self._ewma

    def fault_rate(self) -> float:
        with self._lock:
            return self._ewma

    def health(self) -> str:
        if self._dead or self.scheduler.closed:
            return DEAD
        if self.scheduler.supervisor.state != _SUP_SERVING:
            return REBUILDING
        if self.fault_rate() >= self.config.ewma_degraded:
            return DEGRADED
        max_q = max(1, self.scheduler.config.max_queue)
        if self.scheduler.queue_depth() \
                >= self.config.queue_degraded_frac * max_q:
            return DEGRADED
        return HEALTHY

    def load(self) -> int:
        """Requests this replica is responsible for right now (the
        least-loaded routing key). DEAD replicas report 0 — they are
        never routed anyway."""
        if self._dead or self.scheduler.closed:
            return 0
        return self.scheduler.load()

    # -- lifecycle ------------------------------------------------------------
    def submit(self, req, trace_ctx=None):
        return self.scheduler.submit(req, trace_ctx=trace_ctx)

    def prewarm(self, reqs):
        return self.scheduler.prewarm(reqs)

    def cancel(self, fut) -> bool:
        return self.scheduler.cancel(fut)

    def kill(self, cause: str = "replica_lost",
             timeout: float = 10.0) -> None:
        """Replica-level failure: DEAD now, scheduler closed
        (non-draining) in the background. Idempotent. In-flight
        futures on the dying scheduler resolve with `SchedulerClosed`
        (or a completed result the completion thread already had in
        hand — first set wins), which is the front door's failover
        trigger."""
        with self._lock:
            if self._dead:
                return
            self._dead = True
            record_event("replica_lost", "serving.replica_lost",
                         detail=f"replica {self.name}: {cause}")
            t = threading.Thread(
                target=lambda: self.scheduler.close(drain=False,
                                                    timeout=timeout),
                name=f"replica-kill-{self.name}", daemon=True)
            self._kill_thread = t
        t.start()

    def close(self, drain: bool = True,
              timeout: Optional[float] = None) -> None:
        """Orderly shutdown (drains by default). A killed replica just
        joins the background close."""
        with self._lock:
            kill = self._kill_thread
            self._dead = True
        if kill is not None:
            kill.join(timeout)
            return
        self.scheduler.close(drain=drain, timeout=timeout)

#!/usr/bin/env python
"""Flexible-resolution train sweep CLI over bench.py's builders.

`python bench.py --stage sweep256` runs the canonical north-star stage
(256^2, feature_depths 128-1024, fixed batch ladder). This CLI is the
free-form variant for hardware sessions: any size/depths/batch list,
same per-batch outcome recording and remat retry (VERDICT r3 next
#3/#4), same trainer construction and scalar-readback timing — imported
from bench.py, not duplicated.

Usage (on a healthy TPU window):
  python scripts/bench_sweep256.py --image_size 256 \
      --depths 128,256,512,1024 --batches 1,2,4,8,16,32 \
      --out r4_sweep256.jsonl
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def attempt(image_size, depths, batch, remat, timed_steps, attn_backend):
    """One (batch, remat) cell; returns a dict with numbers or a cause
    (plus backend_died=True when the tunnel — not the workload — was
    the failure, so the caller can stop burning the session window)."""
    import jax

    from bench import _backend_died, build_trainer, make_batches, run
    from flaxdiff_tpu.profiling import device_peak_flops, mfu
    try:
        trainer = build_trainer(tpu_native=True, image_size=image_size,
                                depths=depths, remat=remat,
                                attn_backend=attn_backend)
        ips, step_s, flops = run(trainer,
                                 make_batches(batch, image_size, n=2),
                                 batch, sync_every_step=False,
                                 timed_steps=timed_steps)
    except Exception as e:
        cell = {"error": f"{type(e).__name__}: {e}"[:300], "remat": remat}
        if _backend_died(e):
            cell["backend_died"] = True
        return cell
    finally:
        # free param+opt state before the next cell shrinks the frontier
        try:
            del trainer
        except UnboundLocalError:
            pass
    peak = device_peak_flops()
    return {"imgs_per_sec_per_chip": round(ips, 3),
            "step_time_ms": round(step_s * 1e3, 2),
            "mfu_hw": (round(mfu(flops, step_s, peak), 4)
                       if flops and peak else None),
            "remat": remat}


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--image_size", type=int, default=256)
    ap.add_argument("--depths", default="128,256,512,1024")
    ap.add_argument("--batches", default="1,2,4,8,16,32")
    ap.add_argument("--timed_steps", type=int, default=10)
    ap.add_argument("--attn_backend", default="auto")
    ap.add_argument("--trace", default="")
    ap.add_argument("--out", default="")
    args = ap.parse_args(argv)

    from flaxdiff_tpu.utils import apply_jax_platforms_env
    apply_jax_platforms_env()
    import jax

    depths = tuple(int(x) for x in args.depths.split(","))
    batches = [int(x) for x in args.batches.split(",")]
    platform = jax.devices()[0].platform
    res = {"metric": f"sweep{args.image_size}", "platform": platform,
           "image_size": args.image_size, "depths": list(depths),
           "attn_backend": args.attn_backend, "per_batch": {}}

    failures = 0
    for batch in batches:
        cell = attempt(args.image_size, depths, batch, False,
                       args.timed_steps, args.attn_backend)
        res["per_batch"][str(batch)] = cell
        log(f"batch {batch}: {cell}")
        if cell.get("backend_died"):
            res["aborted"] = "backend died; measured cells preserved"
            break
        if "error" in cell:
            # remat answers "was that OOM?" empirically: it trades
            # FLOPs for activation memory, so a batch that only fits
            # rematerialized pins the cause on memory
            cell_r = attempt(args.image_size, depths, batch, True,
                             args.timed_steps, args.attn_backend)
            res["per_batch"][f"{batch}_remat"] = cell_r
            log(f"batch {batch} remat: {cell_r}")
            if cell_r.get("backend_died"):
                res["aborted"] = "backend died; measured cells preserved"
                break
            failures += 1
            if failures >= 2 and "error" in cell_r:
                break
    ok_num = {int(k): v for k, v in res["per_batch"].items()
              if "error" not in v and "_" not in k}
    ok_all = {k: v for k, v in res["per_batch"].items() if "error" not in v}
    if ok_all:
        best_key = max(ok_all, key=lambda k:
                       ok_all[k]["imgs_per_sec_per_chip"])
        res["best"] = dict(ok_all[best_key], batch=best_key)
    if args.trace and ok_num:
        try:
            from bench import build_trainer, make_batches
            from flaxdiff_tpu.profiling import trace
            best_b = max(ok_num,
                         key=lambda k: ok_num[k]["imgs_per_sec_per_chip"])
            trainer = build_trainer(tpu_native=True,
                                    image_size=args.image_size,
                                    depths=depths,
                                    attn_backend=args.attn_backend)
            put = [trainer.put_batch(b)
                   for b in make_batches(best_b, args.image_size, n=2)]
            for i in range(2):
                loss = trainer.train_step(put[i % 2])
            float(jax.device_get(loss))
            with trace(args.trace):
                for i in range(5):
                    loss = trainer.train_step(put[i % 2])
                float(jax.device_get(loss))
            res["trace_dir"] = args.trace
        except Exception as e:
            # the tunnel dying during the trace must not erase the
            # measured per-batch cells below
            res["trace_error"] = f"{type(e).__name__}: {e}"[:200]
    line = json.dumps(res)
    print(line, flush=True)
    if args.out:
        with open(args.out, "a") as f:
            f.write(line + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())

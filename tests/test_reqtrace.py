"""Request-level tracing + program evidence registry (ISSUE 13).

Covers the acceptance bars:
- every program in a warm `SamplerProgramEngine` cache has a
  `programs.jsonl` record (cache key, compile ms, FLOPs estimate);
- a traced end-to-end serving replay produces a Chrome trace whose
  per-request span sums reconcile with the `serving/*_ms` histograms
  within timer resolution;
- the counting mock proves a traced run performs the IDENTICAL
  seam-counted host syncs as an untraced run, and warm replays with
  tracing enabled still report zero re-traces;
- `TraceRecorder` bounded-event drops surface as
  `telemetry/trace_dropped_events`;
- `scripts/diagnose_run.py` renders Request-traces and Programs
  sections in text and --json.
"""
import json
import os

import numpy as np
import pytest

from flaxdiff_tpu.serving import (SampleRequest, SchedulerConfig,
                                  ServingScheduler)
from flaxdiff_tpu.serving import scheduler as sched_mod
from flaxdiff_tpu.telemetry import (ProgramRegistry, Telemetry,
                                    read_registry, stable_json)
from flaxdiff_tpu.telemetry.reqtrace import RequestTracer
from flaxdiff_tpu.telemetry.tracing import TraceRecorder


@pytest.fixture(scope="module")
def tiny_pipe():
    import jax
    import jax.numpy as jnp

    from flaxdiff_tpu.inference import (DiffusionInferencePipeline,
                                        build_model)
    config = {
        "model": {"name": "simple_dit", "emb_features": 32,
                  "num_heads": 4, "num_layers": 2, "patch_size": 4,
                  "output_channels": 1},
        "schedule": {"name": "cosine", "timesteps": 100},
        "predictor": "epsilon",
    }
    # 2 layers: splittable trunk so cache-plan requests also run
    model = build_model("simple_dit", emb_features=32, num_heads=4,
                        num_layers=2, patch_size=4, output_channels=1)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8, 8, 1)),
                        jnp.zeros((1,)), None)
    return DiffusionInferencePipeline.from_config(config, params=params)


def _requests():
    return [SampleRequest(resolution=8, channels=1, diffusion_steps=n,
                          sampler=s, seed=seed, use_ema=False)
            for n, s, seed in ((3, "ddim", 1), (5, "ddim", 2),
                               (4, "euler_ancestral", 3))]


def _run(sched, reqs):
    futs = [sched.submit(r) for r in reqs]
    sched.start()
    return [f.result(timeout=300) for f in futs]


# ---------------------------------------------------------------------------
# Acceptance: registry coverage + reconciliation on a traced replay
# ---------------------------------------------------------------------------

def test_traced_replay_registry_and_reconciliation(tiny_pipe, tmp_path):
    tel = Telemetry.create(str(tmp_path))
    sched = ServingScheduler(
        pipeline=tiny_pipe, telemetry=tel, autostart=False,
        config=SchedulerConfig(round_steps=2, batch_buckets=(2,)))
    outs = _run(sched, _requests())
    sched.close()
    tel.close()

    # -- every warm-cache program has a registry record ---------------------
    rows = read_registry(str(tmp_path / "programs.jsonl"))
    assert len(rows) == sched.engine.program_cache_size
    registered = {(r["kind"], r["key"]) for r in rows}
    for key in sched.engine._programs:
        kind = key[0]
        assert (kind, str(key)) in registered, key
    for r in rows:
        assert r["compile_ms"] and r["compile_ms"] > 0
        assert r["flops_jaxpr"] and r["flops_jaxpr"] > 0
        assert r["fingerprint"]["platform"]
    # both program kinds this workload compiles are present
    assert {r["kind"] for r in rows} == {"chunk", "terminal"}

    # -- per-request rows reconcile with the histograms ---------------------
    recs = [json.loads(line) for line in
            open(tmp_path / "telemetry.jsonl", encoding="utf-8")]
    traces = [r for r in recs if r.get("type") == "request_trace"]
    assert len(traces) == len(outs)
    for t in traces:
        # the identity is exact by construction: all four values derive
        # from the same three timestamps
        assert t["queue_ms"] + t["compile_ms"] + t["device_ms"] \
            == pytest.approx(t["latency_ms"], abs=0.51)
        assert t["rounds"] >= 1 and len(t["round_detail"]) == t["rounds"]
        for d in t["round_detail"]:
            assert d["kind"] == "chunk" and "key" in d and "bucket" in d
    for span, hist in (("latency_ms", "serving/latency_ms"),
                       ("queue_ms", "serving/queue_ms"),
                       ("compile_ms", "serving/compile_ms"),
                       ("device_ms", "serving/device_ms")):
        h = tel.registry.histogram(hist)
        assert h.count == len(traces)
        assert sum(t[span] for t in traces) == pytest.approx(
            h.total, abs=0.51 * len(traces))

    # -- the Chrome trace has the request + round span families -------------
    doc = json.load(open(tmp_path / "trace.json", encoding="utf-8"))
    names = {e.get("name") for e in doc["traceEvents"]}
    assert {"req.submit", "req.queue", "req.serve", "serve.round",
            "serve.finalize"} <= names


# ---------------------------------------------------------------------------
# Counting mock: tracing adds ZERO host syncs; warm stays retrace-free
# ---------------------------------------------------------------------------

def test_tracing_adds_no_host_syncs_and_warm_zero_retrace(
        tiny_pipe, tmp_path, monkeypatch):
    counts = {"blocks": 0, "gets": 0}
    real_block = sched_mod._block_until_ready
    real_get = sched_mod._device_get

    def count_block(x):
        counts["blocks"] += 1
        return real_block(x)

    def count_get(x):
        counts["gets"] += 1
        return real_get(x)

    monkeypatch.setattr(sched_mod, "_block_until_ready", count_block)
    monkeypatch.setattr(sched_mod, "_device_get", count_get)

    def replay(tel):
        sched = ServingScheduler(
            pipeline=tiny_pipe, telemetry=tel, autostart=False,
            config=SchedulerConfig(round_steps=2, batch_buckets=(2,)))
        outs = _run(sched, _requests())
        misses_cold = tel.registry.counter(
            "serving/program_cache_misses").value
        before = dict(counts)
        outs_warm = _run(sched, _requests())
        sched.close()
        return (outs, outs_warm,
                tel.registry.counter(
                    "serving/program_cache_misses").value - misses_cold,
                {k: counts[k] - before[k] for k in counts})

    counts.update(blocks=0, gets=0)
    untraced = replay(Telemetry(enabled=False))
    syncs_untraced = dict(counts)
    counts.update(blocks=0, gets=0)
    traced = replay(Telemetry.create(str(tmp_path)))
    syncs_traced = dict(counts)

    # identical seam-counted host syncs, traced vs untraced
    assert syncs_traced == syncs_untraced
    # warm replays with tracing enabled still re-trace nothing
    assert traced[2] == 0 and untraced[2] == 0
    # and tracing never changed the samples
    for a, b in zip(untraced[0], traced[0]):
        np.testing.assert_array_equal(a.samples, b.samples)
    for a, b in zip(traced[0], traced[1]):
        np.testing.assert_array_equal(a.samples, b.samples)


# ---------------------------------------------------------------------------
# Shed + drop-counter + unit pieces (no jax needed)
# ---------------------------------------------------------------------------

def test_shed_requests_close_their_trace(tmp_path):
    tel = Telemetry.create(str(tmp_path))
    sched = ServingScheduler(
        engine=_FakeEngine(), telemetry=tel, autostart=False,
        config=SchedulerConfig(max_queue=1))
    keep = sched.submit(SampleRequest(resolution=8, diffusion_steps=2))
    doomed = sched.submit(SampleRequest(resolution=8, diffusion_steps=2))
    with pytest.raises(Exception):
        doomed.result(timeout=1)
    sched.start()
    keep.result(timeout=10)
    sched.close()
    tel.close()
    recs = [json.loads(line) for line in
            open(tmp_path / "telemetry.jsonl", encoding="utf-8")]
    shed = [r for r in recs if r.get("type") == "request_trace"
            and r.get("outcome", "").startswith("shed:")]
    assert len(shed) == 1 and shed[0]["outcome"] == "shed:queue_full"


class _FakeEngine:
    """Minimal jax-free engine (mirrors tests/test_serving.py)."""

    def __init__(self):
        from flaxdiff_tpu.serving import RequestState
        self._rs = RequestState
        self.telemetry = Telemetry(enabled=False)

    def group_key(self, req):
        return (req.resolution, req.sampler, req.num_samples)

    def prepare(self, req, future, submit_t, admit_t):
        return self._rs(req=req, future=future, submit_t=submit_t,
                        admit_t=admit_t, group=self.group_key(req),
                        x=None, rng=None, state=None, pairs=None,
                        terminal_t=0.0, cond=None, uncond=None)

    def advance(self, rows, bucket, round_steps):
        finished = []
        for r in rows:
            r.done += min(r.remaining, round_steps)
            r.rounds += 1
            if r.remaining <= 0:
                finished.append(r)
        return finished, 0.0

    def finalize(self, rows, bucket):
        return np.stack([np.zeros((r.req.num_samples, 2, 2, 1))
                         for r in rows]), 0.0


def test_trace_recorder_drop_counter(tmp_path):
    from flaxdiff_tpu.telemetry import MetricsRegistry
    reg = MetricsRegistry()
    rec = TraceRecorder(str(tmp_path / "t.json"), max_events=3,
                        on_drop=lambda n: reg.counter(
                            "telemetry/trace_dropped_events").inc(n))
    for i in range(6):
        rec.instant(f"e{i}")
    assert rec.dropped == 4     # 1 metadata + 2 stored, 4 past bound
    assert reg.counter("telemetry/trace_dropped_events").value == 4
    rec.save()
    doc = json.load(open(tmp_path / "t.json", encoding="utf-8"))
    assert doc["flaxdiff_dropped_events"] == 4


def test_program_registry_dedupe_and_stability(tmp_path):
    path = str(tmp_path / "programs.jsonl")
    reg = ProgramRegistry(path)
    row = reg.record("chunk", ("chunk", 2, 4), compile_ms=12.3456,
                     flops_jaxpr=1e6)
    assert row is not None
    assert reg.record("chunk", ("chunk", 2, 4), compile_ms=99.0) is None
    reg2 = ProgramRegistry(str(tmp_path / "p2.jsonl"))
    row2 = reg2.record("chunk", ("chunk", 2, 4), compile_ms=12.3456,
                       flops_jaxpr=1e6)
    # byte-stable contract: same inputs -> identical serialized row
    assert stable_json(row) == stable_json(row2)
    assert len(read_registry(path)) == 1


def test_tracer_noop_on_disabled_hub():
    tracer = RequestTracer(Telemetry(enabled=False))
    assert not tracer.enabled
    assert tracer.begin(SampleRequest(resolution=8), 0.0) is None
    tracer.shed(None, "queue_full", 0.0)     # all no-ops, no raise
    tracer.round([], None, 0.0, 1.0, 1)
    tracer.complete(object(), 0, 0, 0, 0, 0.0)


# ---------------------------------------------------------------------------
# Trainer + solo compile-site registration
# ---------------------------------------------------------------------------

def test_trainer_registers_step_programs(tmp_path, mesh):
    import flax.linen as nn
    import jax
    import jax.numpy as jnp
    import optax

    from flaxdiff_tpu.predictors import EpsilonPredictionTransform
    from flaxdiff_tpu.schedulers import CosineNoiseSchedule
    from flaxdiff_tpu.trainer import DiffusionTrainer, TrainerConfig

    class Tiny(nn.Module):
        @nn.compact
        def __call__(self, x, t, cond=None):
            h = nn.Conv(8, (3, 3))(x)
            return nn.Conv(x.shape[-1], (3, 3))(jnp.tanh(h))

    model = Tiny()
    tel = Telemetry.create(str(tmp_path))
    tr = DiffusionTrainer(
        apply_fn=lambda p, x, t, c: model.apply({"params": p}, x, t),
        init_fn=lambda k: model.init(k, jnp.zeros((1, 8, 8, 1)),
                                     jnp.zeros((1,)))["params"],
        tx=optax.adam(1e-3), schedule=CosineNoiseSchedule(timesteps=100),
        transform=EpsilonPredictionTransform(), mesh=mesh,
        config=TrainerConfig(normalize=False, log_every=2,
                             numerics_cadence=3),
        telemetry=tel)
    rng = np.random.default_rng(0)

    def data():
        while True:
            # batch divisible by the conftest mesh's 8 fake devices
            yield {"sample": rng.normal(size=(8, 8, 8, 1))
                   .astype(np.float32)}

    tr.fit(data(), 6)
    tel.close()
    kinds = {r["kind"]: r
             for r in read_registry(str(tmp_path / "programs.jsonl"))}
    # the plain step (with its measured first-step compile) AND the
    # monitored twin are both on the books, with jaxpr FLOPs
    assert kinds["train_step"]["compile_ms"] > 0
    assert kinds["train_step"]["flops_jaxpr"] > 0
    assert kinds["train_step_monitored"]["flops_jaxpr"] > 0


def test_solo_generate_registers_program_and_stays_bit_identical(
        tiny_pipe, tmp_path):
    from flaxdiff_tpu.inference import DiffusionInferencePipeline
    from flaxdiff_tpu.telemetry import use_telemetry

    baseline = np.asarray(tiny_pipe.generate_samples(
        num_samples=1, resolution=8, channels=1, diffusion_steps=3,
        sampler="ddim", seed=5, use_ema=False))
    # a FRESH pipeline: the registering wrapper is installed at program
    # BUILD time, so the registry must be active before the first call
    pipe = DiffusionInferencePipeline.from_config(
        {"model": {"name": "simple_dit", "emb_features": 32,
                   "num_heads": 4, "num_layers": 2, "patch_size": 4,
                   "output_channels": 1},
         "schedule": {"name": "cosine", "timesteps": 100},
         "predictor": "epsilon"}, params=tiny_pipe.params)
    tel = Telemetry.create(str(tmp_path))
    with use_telemetry(tel):
        out = np.asarray(pipe.generate_samples(
            num_samples=1, resolution=8, channels=1, diffusion_steps=3,
            sampler="ddim", seed=5, use_ema=False))
    tel.close()
    # the registering wrapper is transparent: same bits as the raw path
    np.testing.assert_array_equal(out, baseline)
    solo = [r for r in read_registry(str(tmp_path / "programs.jsonl"))
            if r["kind"] == "solo"]
    assert len(solo) == 1
    assert solo[0]["compile_ms"] > 0 and "DDIMSampler" in solo[0]["key"]


# ---------------------------------------------------------------------------
# diagnose_run sections
# ---------------------------------------------------------------------------

def test_diagnose_run_reqtrace_and_programs_sections(tmp_path, capsys):
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))
    from scripts.diagnose_run import main

    tel = Telemetry.create(str(tmp_path))
    tel.write_record({"type": "request_trace", "trace_id": "req-1-0",
                      "outcome": "ok", "queue_ms": 1.0,
                      "compile_ms": 10.0, "device_ms": 5.0,
                      "latency_ms": 16.0, "rounds": 2,
                      "sampler": "ddim", "nfe": 4, "resolution": 8,
                      "round_detail": [
                          {"round": 1, "kind": "chunk", "bucket": 2,
                           "rows": 1, "ms": 3.0, "miss": True,
                           "key": "('chunk', 2, 2)"},
                          {"round": 2, "kind": "chunk", "bucket": 2,
                           "rows": 1, "ms": 2.0}]})
    tel.write_record({"type": "request_trace", "trace_id": "req-1-1",
                      "outcome": "shed:deadline", "queue_ms": 50.0,
                      "sampler": "ddim", "nfe": 4, "resolution": 8})
    tel.programs.record("chunk", ("chunk", 2, 2), compile_ms=123.4,
                        flops_jaxpr=2.5e9, flops_cost=3.0e9,
                        collectives=8,
                        comm_bytes_by_axis={"seq": 4096})
    tel.close()

    assert main([str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "== Request traces (1 completed, 1 shed) ==" in out
    assert "slowest: req-1-0" in out
    assert "round    1 chunk" in out and "MISS" in out
    assert "== Programs (1 registered" in out
    assert "2.500" in out and "123.4" in out
    # static comm model columns (ISSUE 14): dispatch count + KiB/axis
    assert "comm KiB/axis" in out
    assert "seq=4.0" in out

    assert main([str(tmp_path), "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["request_traces"]["completed"] == 1
    assert doc["request_traces"]["shed"] == 1
    assert doc["request_traces"]["spans"]["latency_ms"]["p50"] == 16.0
    assert doc["request_traces"]["slowest"]["trace_id"] == "req-1-0"
    assert doc["programs"][0]["kind"] == "chunk"
    assert doc["programs"][0]["collectives"] == 8
    assert doc["programs"][0]["comm_bytes_by_axis"] == {"seq": 4096}

"""Sharded packed-record corpora over a filesystem abstraction.

The reference's at-scale path is pygrain.ArrayRecordDataSource over
hundreds of ArrayRecord shards on a gcsfuse-mounted bucket (reference
data/sources/images.py:219-270; data/dataset_map.py:19-105 — e.g.
combined_msml612: 883 GiB / 20M+ samples across 569+ shards). This is
the first-party analogue: many packed-record shard files presented as
ONE indexable source, so grain's IndexSampler + ShardByJaxProcess hands
each process a disjoint slice of the global record space exactly as the
reference's corpus table does.

Two read paths:
  - local paths (incl. fuse mounts, the reference's actual GCS access
    mode): the native mmap reader (data/packed_records.py);
  - any `FileSystem`-shaped object (open/glob): a pure-Python seek/read
    reader — the mockable remote path for object stores that cannot
    mmap. Tests drive it with an in-memory FS standing in for a bucket.

Shards open LAZILY and an LRU bound caps simultaneously-open readers:
a 20M-record epoch touches shards as the sampler reaches them instead
of holding 569 file handles/mmaps from startup.
"""
from __future__ import annotations

import bisect
import dataclasses
import glob as _glob
import struct
import threading
import zlib
from collections import OrderedDict
from typing import Any, List, Optional, Sequence

from ..resilience import faults as _res_faults
from .sources.base import DataSource

_HEADER = struct.Struct("<4sIQ")          # magic, version, n_records
_INDEX_V2 = struct.Struct("<QQII")        # offset, length, crc32, pad
_INDEX_V1 = struct.Struct("<QQ")


class LocalFileSystem:
    """Default FileSystem: plain local (or fuse-mounted) paths."""

    def open(self, path: str, mode: str = "rb"):
        return open(path, mode)

    def glob(self, pattern: str) -> List[str]:
        return sorted(_glob.glob(pattern))


class PythonPackedReader:
    """Pure-Python packed-record reader over a FileSystem file object —
    the remote-capable counterpart of the native mmap reader (same v1/v2
    layout as data/packed_records.py). Header+index are read once; each
    record is one seek+read."""

    def __init__(self, fs, path: str):
        self._fs = fs
        self._path = path
        self._fh = fs.open(path, "rb")
        self._lock = threading.Lock()   # grain read threads share readers
        head = self._fh.read(_HEADER.size)
        magic, version, n = _HEADER.unpack(head)
        if magic != b"FDTR":
            raise IOError(f"{path!r} is not a packed record file")
        if version not in (1, 2):
            raise IOError(f"{path!r}: unsupported version {version}")
        self.version = version
        entry = _INDEX_V2 if version == 2 else _INDEX_V1
        raw = self._fh.read(entry.size * n)
        self._index = [entry.unpack_from(raw, i * entry.size)
                       for i in range(n)]
        self._base = _HEADER.size + entry.size * n

    def __len__(self) -> int:
        return len(self._index)

    def record_bytes(self, idx: int) -> bytes:
        off, length = self._index[idx][0], self._index[idx][1]
        with self._lock:
            self._fh.seek(self._base + off)
            data = self._fh.read(length)
        if len(data) != length:
            raise IOError(f"short read at record {idx} of {self._path!r}")
        return data

    def verify(self, idx: int) -> bool:
        if self.version < 2:
            return True
        return (zlib.crc32(self.record_bytes(idx)) & 0xFFFFFFFF) \
            == self._index[idx][2]

    def close(self):
        self._fh.close()

    def __del__(self):
        try:
            self.close()
        except Exception as e:  # noqa: BLE001 — degrade, but visibly
            # a GC-time close failure usually means a leaked handle or
            # a double-close bug; leave a trace instead of swallowing
            # (the profiling.trace idiom — silent-except gate)
            from ..resilience.events import record_event
            record_event("warning", "data.reader_close",
                         detail=f"{type(e).__name__}: {e} "
                                f"(path={self._path})")


@dataclasses.dataclass
class ShardedPackedRecordSource(DataSource):
    """One global random-access index over many packed-record shards.

    `shards`: explicit paths, or a glob `pattern` resolved through the
    filesystem. `filesystem=None` uses the native mmap reader on local
    paths; any FileSystem object switches every shard to the Python
    seek/read path. `max_open` bounds concurrently-open shard readers
    (LRU eviction).

    `quarantine` (a `dataplane.QuarantineJournal`): an undecodable or
    torn record becomes a DETERMINISTIC placeholder (zero image, empty
    caption — batch geometry preserved) noted with provenance
    (shard path, local index, reason) instead of an exception. Replay
    re-encounters the same bad record, decodes to the same placeholder,
    and the journal dedupes — the bit-exact-replay contract. In-process
    only: grain worker subprocesses drop the journal on pickle (their
    quarantines still yield placeholders, but provenance lands in the
    worker, so the deterministic data plane runs `worker_count=0`)."""

    shards: Optional[Sequence[str]] = None
    pattern: Optional[str] = None
    filesystem: Optional[Any] = None
    max_open: int = 16
    decode: bool = True
    quarantine: Optional[Any] = None
    placeholder_size: int = 8

    def __post_init__(self):
        fs = self.filesystem or LocalFileSystem()
        paths = list(self.shards) if self.shards else fs.glob(self.pattern)
        if not paths:
            raise FileNotFoundError(
                f"no packed-record shards match {self.pattern!r}")
        self._paths = paths
        # per-shard record counts from the 16-byte HEADER alone (at the
        # 569-shard / 20M-record target shape, parsing every shard's full
        # index at startup would read hundreds of MB serially)
        counts = [self._record_count(fs, p) for p in paths]
        self._starts: List[int] = []
        total = 0
        for c in counts:
            self._starts.append(total)
            total += c
        self._total = total
        self._readers: OrderedDict[str, Any] = OrderedDict()
        self._lock = threading.Lock()

    @staticmethod
    def _record_count(fs, path: str) -> int:
        with fs.open(path, "rb") as f:
            head = f.read(_HEADER.size)
        magic, version, n = _HEADER.unpack(head)
        if magic != b"FDTR":
            raise IOError(f"{path!r} is not a packed record file")
        if version not in (1, 2):
            raise IOError(f"{path!r}: unsupported version {version}")
        return n

    # grain worker processes pickle the data source: drop the lock and
    # the warm reader cache (each worker re-opens shards lazily)
    def __getstate__(self):
        state = self.__dict__.copy()
        state["_readers"] = OrderedDict()
        state["_lock"] = None
        # the journal holds a lock and its provenance is only meaningful
        # in-process (see class docstring): workers decode placeholders
        # without journaling rather than failing to pickle
        state["quarantine"] = None
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._lock = threading.Lock()

    def _open_reader(self, path: str):
        if self.filesystem is None:
            from .packed_records import PackedRecordReader
            try:
                return PackedRecordReader(path)
            except Exception:
                # native lib unavailable (unbuilt wheel): python fallback
                return PythonPackedReader(LocalFileSystem(), path)
        return PythonPackedReader(self.filesystem, path)

    def _reader(self, path: str):
        with self._lock:
            r = self._readers.get(path)
            if r is not None:
                self._readers.move_to_end(path)
                return r
        r = self._open_reader(path)
        with self._lock:
            if path in self._readers:       # lost a race: keep the winner
                r.close()
                return self._readers[path]
            self._readers[path] = r
            while len(self._readers) > self.max_open:
                # DROP the evicted reader, don't close() it: another grain
                # read thread may hold it mid-record_bytes (close would
                # be an I/O-on-closed-file error on the python path and a
                # munmap use-after-free on the native one). Its __del__
                # closes it once the last in-flight user releases it.
                self._readers.popitem(last=False)
        return r

    def locate(self, i: int):
        """Global record index -> (shard_path, local_index)."""
        if not 0 <= i < self._total:
            raise IndexError(f"record {i} out of range (n={self._total})")
        s = bisect.bisect_right(self._starts, i) - 1
        return self._paths[s], i - self._starts[s]

    def get_source(self, path_override: Optional[str] = None):
        if path_override:
            return dataclasses.replace(
                self, shards=None, pattern=path_override).get_source()
        outer = self

        class _Src:
            def __len__(self):
                return outer._total

            def __getitem__(self, i):
                path, local = outer.locate(int(i))
                from .packed_records import (decode_standard_record,
                                             unpack_record)
                try:
                    # chaos site: a plan arming "data.decode" corrupts
                    # this record deterministically (per_key scheduling)
                    _res_faults.check("data.decode", key=f"{path}:{local}")
                    entries = unpack_record(
                        outer._reader(path).record_bytes(local))
                    if not outer.decode:
                        return entries
                    return decode_standard_record(entries)
                except Exception as e:
                    if outer.quarantine is None:
                        raise
                    outer.quarantine.note(
                        path, f"rec:{local}", f"{type(e).__name__}: {e}")
                    from .dataplane import placeholder_record
                    return placeholder_record(outer.placeholder_size)

        return _Src()

"""Model families (capability parity: reference flaxdiff/models/)."""
from . import common, sfc
from .attention import AttentionLayer, BasicTransformerBlock, TransformerBlock
from .autoencoder import (
    AUTOENCODER_REGISTRY,
    AutoEncoder,
    IdentityAutoEncoder,
    KLAutoEncoder,
    StableDiffusionVAE,
)
from .dit import DiTBlock, SimpleDiT
from .sd_vae import SDVAE, SDDecoder, SDEncoder, convert_sd_vae_torch_state_dict
from .mmdit import (
    HierarchicalMMDiT,
    MMAdaLNZero,
    MMDiTBlock,
    PatchExpanding,
    PatchMerging,
    SimpleMMDiT,
)
from .ssm import (
    BidirectionalS5Layer,
    HybridSSMAttentionDiT,
    S5Layer,
    SpatialFusionConv,
    SSMDiTBlock,
    build_block_pattern,
)
from .unet import Unet
from .unet3d import TemporalAttention, TemporalConvLayer, UNet3D, UNet3DBlock
from .uvit import SimpleUDiT, UViT
from .vit_common import (
    AdaLNParams,
    AdaLNZero,
    PatchEmbedding,
    PositionalEncoding,
    RoPEAttention,
    apply_rope,
    rope_frequencies,
)

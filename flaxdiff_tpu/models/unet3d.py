"""3D (video) UNet with temporal convolutions and temporal attention.

Capability parity with reference flaxdiff/models/unet_3d.py:24-445 and
unet_3d_blocks.py:26-505 (FlaxUNet3DConditionModel: [B,F,H,W,C] input,
frames folded into the batch for spatial ops, temb repeated per frame,
per-frame cross-attention, TemporalConvLayer with zero-init last conv,
temporal attention over the frame axis, ControlNet-style additional
residual hooks). Built from this framework's own blocks rather than
subclassed diffusers modules; layouts keep H*W or F as the contiguous
minor-most batch/sequence dims so the MXU sees large batched matmuls.
"""
from __future__ import annotations

from typing import Callable, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..typing import Dtype
from .attention import TransformerBlock
from .common import (
    Downsample,
    FourierEmbedding,
    ResidualBlock,
    TimeProjection,
    Upsample,
)
from .vit_common import RoPEAttention


class TemporalConvLayer(nn.Module):
    """Stack of (3,1,1) temporal convs with a zero-init final conv so the
    layer starts as identity (reference unet_3d_blocks.py:103-167).

    Operates on [B*F, H, W, C] given the static frame count.
    """

    features: int
    norm_groups: int = 8
    dtype: Optional[Dtype] = None

    @nn.compact
    def __call__(self, x: jax.Array, num_frames: int) -> jax.Array:
        bf, h, w, c = x.shape
        b = bf // num_frames
        x5 = x.reshape(b, num_frames, h, w, c)
        identity = x5

        def norm_silu_conv(h5, out_ch, name, zero=False):
            h5 = nn.GroupNorm(num_groups=self.norm_groups, dtype=jnp.float32,
                              name=f"{name}_norm")(h5)
            h5 = jax.nn.silu(h5)
            init = (nn.initializers.zeros if zero
                    else nn.initializers.lecun_normal())
            return nn.Conv(out_ch, (3, 1, 1),
                           padding=((1, 1), (0, 0), (0, 0)),
                           kernel_init=init, dtype=self.dtype,
                           name=f"{name}_conv")(h5)

        h5 = norm_silu_conv(x5, self.features, "t1")
        h5 = norm_silu_conv(h5, c, "t2")
        h5 = norm_silu_conv(h5, c, "t3", zero=True)
        return (identity + h5).reshape(bf, h, w, c)


class TemporalAttention(nn.Module):
    """Self-attention over the frame axis: tokens are frames, batch is
    B*H*W (reference unet_3d_blocks.py:26-101). RoPE gives frames a
    relative temporal order."""

    features: int
    heads: int = 4
    norm_groups: int = 8
    backend: str = "auto"
    dtype: Optional[Dtype] = None
    precision: Optional[jax.lax.Precision] = None

    @nn.compact
    def __call__(self, x: jax.Array, num_frames: int) -> jax.Array:
        bf, h, w, c = x.shape
        b = bf // num_frames
        x5 = x.reshape(b, num_frames, h, w, c)
        residual = x5
        h5 = nn.GroupNorm(num_groups=self.norm_groups, dtype=jnp.float32,
                          name="norm")(x5)
        # [B, F, H, W, C] -> [B*H*W, F, C]
        tokens = h5.transpose(0, 2, 3, 1, 4).reshape(b * h * w, num_frames, c)
        # zero-init the attention's own output projection so the block
        # starts as identity — no second projection matmul needed.
        tokens = RoPEAttention(
            heads=self.heads, dim_head=max(c // self.heads, 1),
            backend=self.backend, dtype=self.dtype, precision=self.precision,
            out_kernel_init=nn.initializers.zeros, name="attn")(tokens)
        h5 = tokens.reshape(b, h, w, num_frames, c).transpose(0, 3, 1, 2, 4)
        return (residual + h5).reshape(bf, h, w, c)


class UNet3DBlock(nn.Module):
    """One level unit: spatial resblock -> temporal conv -> optional
    (spatial cross-attn -> temporal attn), the interleaving the reference
    uses (unet_3d_blocks.py:234-246)."""

    features: int
    heads: int = 4
    use_attention: bool = False
    norm_groups: int = 8
    backend: str = "auto"
    dtype: Optional[Dtype] = None
    precision: Optional[jax.lax.Precision] = None
    activation: Callable = jax.nn.swish

    @nn.compact
    def __call__(self, x: jax.Array, temb: jax.Array, context,
                 num_frames: int) -> jax.Array:
        x = ResidualBlock(features=self.features,
                          norm_groups=self.norm_groups,
                          activation=self.activation, dtype=self.dtype,
                          precision=self.precision, name="res")(x, temb)
        x = TemporalConvLayer(features=self.features,
                              norm_groups=self.norm_groups, dtype=self.dtype,
                              name="temp_conv")(x, num_frames)
        if self.use_attention:
            x = TransformerBlock(
                heads=self.heads,
                dim_head=self.features // self.heads,
                backend=self.backend, dtype=self.dtype,
                precision=self.precision, use_projection=True,
                name="spatial_attn")(x, context)
            x = TemporalAttention(
                features=self.features, heads=self.heads,
                norm_groups=self.norm_groups, backend=self.backend,
                dtype=self.dtype, precision=self.precision,
                name="temporal_attn")(x, num_frames)
        return x


class UNet3D(nn.Module):
    """Text-conditional video UNet over [B, F, H, W, C].

    Frames fold into the batch for all spatial ops (reference
    unet_3d.py:344-346); temb and text context are repeated per frame
    (unet_3d.py:316). `down_block_additional_residuals` /
    `mid_block_additional_residual` are ControlNet-style hooks
    (unet_3d.py:392-415).
    """

    output_channels: int = 3
    emb_features: int = 256
    feature_depths: Sequence[int] = (64, 128, 256)
    attention_levels: Sequence[bool] = (False, True, True)
    num_res_blocks: int = 2
    heads: int = 4
    norm_groups: int = 8
    backend: str = "auto"
    dtype: Optional[Dtype] = None
    precision: Optional[jax.lax.Precision] = None
    activation: Callable = jax.nn.swish
    # jax.checkpoint each level block (num_frames is static arg 4)
    remat: bool = False

    @nn.compact
    def __call__(self, x: jax.Array, temb: jax.Array,
                 textcontext: Optional[jax.Array] = None,
                 down_block_additional_residuals: Optional[Tuple] = None,
                 mid_block_additional_residual: Optional[jax.Array] = None
                 ) -> jax.Array:
        if x.ndim != 5:
            raise ValueError(f"UNet3D expects [B,F,H,W,C], got {x.shape}")
        B, F, H, W, C = x.shape

        t = FourierEmbedding(features=self.emb_features, name="t_fourier")(temb)
        t = TimeProjection(features=self.emb_features, name="t_proj")(t)
        # fold frames into batch; repeat per-frame conditioning
        xf = x.reshape(B * F, H, W, C)
        tf = jnp.repeat(t, F, axis=0)
        ctx = (jnp.repeat(textcontext, F, axis=0)
               if textcontext is not None else None)

        h = nn.Conv(self.feature_depths[0], (3, 3), padding="SAME",
                    dtype=self.dtype, name="conv_in")(xf)

        BlockCls = (nn.remat(UNet3DBlock, static_argnums=(4,))
                    if self.remat else UNet3DBlock)
        skips = [h]
        for i, feats in enumerate(self.feature_depths):
            for j in range(self.num_res_blocks):
                h = BlockCls(
                    features=feats, heads=self.heads,
                    use_attention=self.attention_levels[i],
                    norm_groups=self.norm_groups, backend=self.backend,
                    dtype=self.dtype, precision=self.precision,
                    activation=self.activation,
                    name=f"down_{i}_{j}")(h, tf, ctx, F)
                skips.append(h)
            if i < len(self.feature_depths) - 1:
                h = Downsample(feats, dtype=self.dtype,
                               precision=self.precision,
                               name=f"downsample_{i}")(h)
                skips.append(h)

        if down_block_additional_residuals is not None:
            if len(down_block_additional_residuals) != len(skips):
                raise ValueError(
                    f"expected {len(skips)} additional residuals, got "
                    f"{len(down_block_additional_residuals)}")
            skips = [s + r for s, r in
                     zip(skips, down_block_additional_residuals)]

        h = BlockCls(features=self.feature_depths[-1], heads=self.heads,
                        use_attention=True, norm_groups=self.norm_groups,
                        backend=self.backend, dtype=self.dtype,
                        precision=self.precision,
                        activation=self.activation, name="mid")(h, tf, ctx, F)
        if mid_block_additional_residual is not None:
            h = h + mid_block_additional_residual

        for i, feats in enumerate(reversed(self.feature_depths)):
            level = len(self.feature_depths) - 1 - i
            for j in range(self.num_res_blocks + 1):
                h = jnp.concatenate([h, skips.pop()], axis=-1)
                h = BlockCls(
                    features=feats, heads=self.heads,
                    use_attention=self.attention_levels[level],
                    norm_groups=self.norm_groups, backend=self.backend,
                    dtype=self.dtype, precision=self.precision,
                    activation=self.activation,
                    name=f"up_{i}_{j}")(h, tf, ctx, F)
            if level > 0:
                h = Upsample(feats, dtype=self.dtype,
                             precision=self.precision,
                             name=f"upsample_{i}")(h)

        h = nn.GroupNorm(num_groups=self.norm_groups, dtype=jnp.float32,
                         name="norm_out")(h)
        h = nn.Conv(self.output_channels, (3, 3), padding="SAME",
                    dtype=jnp.float32, kernel_init=nn.initializers.zeros,
                    name="conv_out")(self.activation(h))
        return h.reshape(B, F, H, W, self.output_channels)

"""First-party SD-VAE: architecture, converter, and cross-framework
parity against a torch twin built with diffusers AutoencoderKL
state-dict naming (upgrades VERDICT r2 component #30 from
diffusers-gated to parity-tested; real weights still need network, but
any layout/padding/eps/attention divergence shows up here)."""
import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flaxdiff_tpu.models.sd_vae import (
    SDVAE,
    assemble_params,
    convert_sd_vae_torch_state_dict,
)

torch = pytest.importorskip("torch")
import torch.nn.functional as F  # noqa: E402
from torch import nn  # noqa: E402

GROUPS = 4
CHANS = (8, 16, 16, 16)
LATENT = 4
LAYERS = 1


# ---------------------------------------------------------------------------
# Torch twin with diffusers AutoencoderKL naming
# ---------------------------------------------------------------------------

class TResnet(nn.Module):
    def __init__(self, cin, cout):
        super().__init__()
        self.norm1 = nn.GroupNorm(GROUPS, cin, eps=1e-6)
        self.conv1 = nn.Conv2d(cin, cout, 3, padding=1)
        self.norm2 = nn.GroupNorm(GROUPS, cout, eps=1e-6)
        self.conv2 = nn.Conv2d(cout, cout, 3, padding=1)
        if cin != cout:
            self.conv_shortcut = nn.Conv2d(cin, cout, 1)

    def forward(self, x):
        h = self.conv1(F.silu(self.norm1(x)))
        h = self.conv2(F.silu(self.norm2(h)))
        if hasattr(self, "conv_shortcut"):
            x = self.conv_shortcut(x)
        return x + h


class TAttn(nn.Module):
    def __init__(self, c):
        super().__init__()
        self.group_norm = nn.GroupNorm(GROUPS, c, eps=1e-6)
        self.to_q = nn.Linear(c, c)
        self.to_k = nn.Linear(c, c)
        self.to_v = nn.Linear(c, c)
        self.to_out = nn.Sequential(nn.Linear(c, c))

    def forward(self, x):
        b, c, h, w = x.shape
        y = self.group_norm(x).reshape(b, c, h * w).permute(0, 2, 1)
        q, k, v = self.to_q(y), self.to_k(y), self.to_v(y)
        attn = torch.softmax(q @ k.transpose(1, 2) / math.sqrt(c), dim=-1)
        out = self.to_out(attn @ v).permute(0, 2, 1).reshape(b, c, h, w)
        return x + out


class TDownsample(nn.Module):
    def __init__(self, c):
        super().__init__()
        self.conv = nn.Conv2d(c, c, 3, stride=2, padding=0)

    def forward(self, x):
        return self.conv(F.pad(x, (0, 1, 0, 1)))


class TUpsample(nn.Module):
    def __init__(self, c):
        super().__init__()
        self.conv = nn.Conv2d(c, c, 3, padding=1)

    def forward(self, x):
        return self.conv(F.interpolate(x, scale_factor=2, mode="nearest"))


class TDownBlock(nn.Module):
    def __init__(self, cin, cout, down):
        super().__init__()
        self.resnets = nn.ModuleList(
            [TResnet(cin if j == 0 else cout, cout) for j in range(LAYERS)])
        if down:
            self.downsamplers = nn.ModuleList([TDownsample(cout)])

    def forward(self, x):
        for r in self.resnets:
            x = r(x)
        if hasattr(self, "downsamplers"):
            x = self.downsamplers[0](x)
        return x


class TUpBlock(nn.Module):
    def __init__(self, cin, cout, up):
        super().__init__()
        self.resnets = nn.ModuleList(
            [TResnet(cin if j == 0 else cout, cout)
             for j in range(LAYERS + 1)])
        if up:
            self.upsamplers = nn.ModuleList([TUpsample(cout)])

    def forward(self, x):
        for r in self.resnets:
            x = r(x)
        if hasattr(self, "upsamplers"):
            x = self.upsamplers[0](x)
        return x


class TMidBlock(nn.Module):
    def __init__(self, c):
        super().__init__()
        self.resnets = nn.ModuleList([TResnet(c, c), TResnet(c, c)])
        self.attentions = nn.ModuleList([TAttn(c)])

    def forward(self, x):
        return self.resnets[1](self.attentions[0](self.resnets[0](x)))


class TEncoder(nn.Module):
    def __init__(self):
        super().__init__()
        self.conv_in = nn.Conv2d(3, CHANS[0], 3, padding=1)
        self.down_blocks = nn.ModuleList([
            TDownBlock(CHANS[max(i - 1, 0)], c, i < len(CHANS) - 1)
            for i, c in enumerate(CHANS)])
        self.mid_block = TMidBlock(CHANS[-1])
        self.conv_norm_out = nn.GroupNorm(GROUPS, CHANS[-1], eps=1e-6)
        self.conv_out = nn.Conv2d(CHANS[-1], 2 * LATENT, 3, padding=1)

    def forward(self, x):
        x = self.conv_in(x)
        for b in self.down_blocks:
            x = b(x)
        x = self.mid_block(x)
        return self.conv_out(F.silu(self.conv_norm_out(x)))


class TDecoder(nn.Module):
    def __init__(self):
        super().__init__()
        rev = CHANS[::-1]
        self.conv_in = nn.Conv2d(LATENT, rev[0], 3, padding=1)
        self.mid_block = TMidBlock(rev[0])
        self.up_blocks = nn.ModuleList([
            TUpBlock(rev[max(i - 1, 0)], c, i < len(rev) - 1)
            for i, c in enumerate(rev)])
        self.conv_norm_out = nn.GroupNorm(GROUPS, rev[-1], eps=1e-6)
        self.conv_out = nn.Conv2d(rev[-1], 3, 3, padding=1)

    def forward(self, z):
        z = self.mid_block(self.conv_in(z))
        for b in self.up_blocks:
            z = b(z)
        return self.conv_out(F.silu(self.conv_norm_out(z)))


class TVAE(nn.Module):
    def __init__(self):
        super().__init__()
        self.encoder = TEncoder()
        self.decoder = TDecoder()
        self.quant_conv = nn.Conv2d(2 * LATENT, 2 * LATENT, 1)
        self.post_quant_conv = nn.Conv2d(LATENT, LATENT, 1)

    def moments(self, x):
        return self.quant_conv(self.encoder(x))

    def decode(self, z):
        return self.decoder(self.post_quant_conv(z))


@pytest.fixture(scope="module")
def twins():
    torch.manual_seed(7)
    tvae = TVAE().eval()
    state = {k: v.numpy() for k, v in tvae.state_dict().items()}
    vae = SDVAE.from_torch_state_dict(state, norm_groups=GROUPS,
                                      scaling_factor=1.0)
    return tvae, vae


def test_config_inferred_from_checkpoint(twins):
    _, vae = twins
    cfg = vae.serialize()
    assert cfg["block_out_channels"] == list(CHANS)
    assert cfg["latent_channels"] == LATENT
    assert cfg["layers_per_block"] == LAYERS
    assert vae.downscale_factor == 8


def test_encode_moments_parity(twins):
    tvae, vae = twins
    rng = np.random.default_rng(0)
    x = rng.standard_normal((2, 32, 32, 3), dtype=np.float32)
    with torch.no_grad():
        want = tvae.moments(torch.from_numpy(x.transpose(0, 3, 1, 2)))
    got = np.asarray(vae.moments(jnp.asarray(x)))
    np.testing.assert_allclose(got, want.numpy().transpose(0, 2, 3, 1),
                               rtol=1e-4, atol=1e-4)


def test_decode_parity(twins):
    tvae, vae = twins
    rng = np.random.default_rng(1)
    z = rng.standard_normal((2, 4, 4, LATENT), dtype=np.float32)
    with torch.no_grad():
        want = tvae.decode(torch.from_numpy(z.transpose(0, 3, 1, 2)))
    got = np.asarray(vae.decode(jnp.asarray(z)))
    np.testing.assert_allclose(got, want.numpy().transpose(0, 2, 3, 1),
                               rtol=1e-4, atol=1e-4)


def test_encode_mean_matches_moments_mean(twins):
    _, vae = twins
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.standard_normal((1, 32, 32, 3), dtype=np.float32))
    mean = np.asarray(vae.moments(x))[..., :LATENT]
    np.testing.assert_allclose(np.asarray(vae.encode(x)), mean,
                               rtol=1e-5, atol=1e-5)


def test_legacy_attention_naming(twins):
    """CompVis-era checkpoints name the attention projections
    query/key/value/proj_attn and store them as 1x1 convs — the
    converter must accept both namings identically."""
    tvae, vae = twins
    state = {}
    for k, v in tvae.state_dict().items():
        v = v.numpy()
        for new, old in (("to_q", "query"), ("to_k", "key"),
                         ("to_v", "value"), ("to_out.0", "proj_attn")):
            if f".{new}." in k:
                k = k.replace(f".{new}.", f".{old}.")
                if v.ndim == 2:  # Linear -> 1x1 conv layout
                    v = v[:, :, None, None]
                break
        state[k] = v
    legacy = SDVAE.from_torch_state_dict(state, norm_groups=GROUPS,
                                         scaling_factor=1.0)
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.standard_normal((1, 32, 32, 3), dtype=np.float32))
    np.testing.assert_allclose(np.asarray(legacy.moments(x)),
                               np.asarray(vae.moments(x)),
                               rtol=1e-6, atol=1e-6)


def test_converter_rejects_unknown_names():
    with pytest.raises(ValueError, match="unmapped"):
        convert_sd_vae_torch_state_dict(
            {"encoder.conv_in.running_gizmo": np.zeros((3,))})


def test_assemble_rejects_missing_and_unused():
    template = {"a": {"kernel": jnp.zeros((2, 2))}}
    with pytest.raises(ValueError, match="missing"):
        assemble_params(template, {}, "")
    with pytest.raises(ValueError, match="unused"):
        assemble_params(template, {"a/kernel": np.zeros((2, 2)),
                                   "b/kernel": np.zeros((1,))}, "")
    with pytest.raises(ValueError, match="mismatch"):
        assemble_params(template, {"a/kernel": np.zeros((3, 3))}, "")


def test_video_flattening_and_registry():
    from flaxdiff_tpu.models.autoencoder import AUTOENCODER_REGISTRY
    vae = AUTOENCODER_REGISTRY["sd_vae"](
        block_out_channels=(8, 8), norm_groups=4, layers_per_block=1,
        image_size=16)
    vid = jnp.zeros((2, 3, 16, 16, 3))
    z = vae.encode(vid)
    assert z.shape == (2, 3, 8, 8, 4)
    assert vae.decode(z).shape == vid.shape
    assert vae.name == "sd_vae"


def test_scaling_factor_applied():
    vae = SDVAE.create(jax.random.PRNGKey(0), block_out_channels=(8, 8),
                       norm_groups=4, layers_per_block=1, image_size=16,
                       scaling_factor=2.0)
    x = jnp.ones((1, 16, 16, 3))
    z = vae.encode(x)
    vae1 = SDVAE(vae.params, block_out_channels=(8, 8), norm_groups=4,
                 layers_per_block=1, scaling_factor=1.0)
    np.testing.assert_allclose(np.asarray(z),
                               2.0 * np.asarray(vae1.encode(x)),
                               rtol=1e-6)

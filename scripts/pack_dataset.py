#!/usr/bin/env python
"""Pack an image folder (or HuggingFace dataset) into packed-record shards
readable by the native C++ reader (flaxdiff_tpu/native/packed_reader.cpp).

The offline equivalent of the reference's dataset tooling
(reference datasets/data-processing.py + img2dataset shell scripts,
dataset_map.py ArrayRecord shards): images are JPEG-encoded with captions
into the framework's own record format, sharded for parallel reads.

Usage:
  python scripts/pack_dataset.py --src ./images_dir --out ./shards \
      --shards 4 --image_size 256
  python scripts/pack_dataset.py --src hf:nelorth/oxford-flowers \
      --out ./shards --caption_key label
"""
from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from flaxdiff_tpu.data.packed_records import PackedRecordWriter  # noqa: E402

IMAGE_EXTS = (".jpg", ".jpeg", ".png", ".webp", ".bmp")


def _rgb_to_bgr(img: np.ndarray) -> np.ndarray:
    """RGB/grayscale/RGBA -> 3-channel BGR for cv2.imencode (a bare
    [..., ::-1] would mirror 2-D grayscale and scramble RGBA)."""
    import cv2
    if img.ndim == 2:
        return cv2.cvtColor(img, cv2.COLOR_GRAY2BGR)
    if img.shape[2] == 4:
        return cv2.cvtColor(img, cv2.COLOR_RGBA2BGR)
    return np.ascontiguousarray(img[..., ::-1])


def iter_folder(src: str, caption_from_name: bool):
    import cv2
    for dirpath, _dirs, files in os.walk(src):
        for f in sorted(files):
            if not f.lower().endswith(IMAGE_EXTS):
                continue
            path = os.path.join(dirpath, f)
            img = cv2.imread(path)
            if img is None:
                continue
            caption = ""
            if caption_from_name:
                # folder-name captioning (class-per-directory layout)
                caption = os.path.basename(dirpath).replace("_", " ")
            txt = os.path.splitext(path)[0] + ".txt"
            if os.path.exists(txt):
                caption = open(txt).read().strip()
            yield img[..., ::-1], caption  # BGR -> RGB


def iter_hf(name: str, image_key: str, caption_key: str):
    import datasets
    ds = datasets.load_dataset(name, split="train")
    for row in ds:
        img = np.asarray(row[image_key])
        caption = str(row.get(caption_key, ""))
        yield img, caption


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--src", required=True,
                    help="image folder, or hf:<dataset-name>")
    ap.add_argument("--out", required=True, help="output shard directory")
    ap.add_argument("--shards", type=int, default=4)
    ap.add_argument("--image_size", type=int, default=0,
                    help="resize shorter side to this (0 = keep)")
    ap.add_argument("--quality", type=int, default=92)
    ap.add_argument("--image_key", default="image")
    ap.add_argument("--caption_key", default="text")
    ap.add_argument("--caption_from_dirname", action="store_true")
    args = ap.parse_args()

    import cv2
    os.makedirs(args.out, exist_ok=True)
    if args.src.startswith("hf:"):
        it = iter_hf(args.src[3:], args.image_key, args.caption_key)
    else:
        it = iter_folder(args.src, args.caption_from_dirname)

    writers = [PackedRecordWriter(
        os.path.join(args.out, f"shard-{i:05d}.pack"))
        for i in range(args.shards)]
    counts = [0] * args.shards
    n = 0
    for img, caption in it:
        if args.image_size:
            h, w = img.shape[:2]
            s = args.image_size / min(h, w)
            img = cv2.resize(img, (round(w * s), round(h * s)),
                             interpolation=cv2.INTER_AREA)
        ok, enc = cv2.imencode(".jpg", _rgb_to_bgr(img),
                               [cv2.IMWRITE_JPEG_QUALITY, args.quality])
        if not ok:
            continue
        shard = n % args.shards
        writers[shard].write({"jpg": enc.tobytes(),
                              "txt": caption.encode("utf-8")})
        counts[shard] += 1
        n += 1
        if n % 1000 == 0:
            print(f"packed {n}...", file=sys.stderr)
    for w in writers:
        w.close()
    meta = {"total": n, "shards": args.shards, "counts": counts,
            "image_size": args.image_size}
    with open(os.path.join(args.out, "meta.json"), "w") as fh:
        json.dump(meta, fh)
    print(json.dumps(meta))


if __name__ == "__main__":
    main()

"""Serving request/result types and the thread-safe result future.

A `SampleRequest` is one unit of admission: a block of `num_samples`
samples sharing one prompt list, seed, sampler, and NFE budget. The
scheduler batches COMPATIBLE requests (same shape/sampler/guidance
family — see `serving.engine.group_key`) into micro-batch rounds; NFE
may differ within a group because the engine masks each row to its own
trajectory length.

Determinism contract: a request's samples depend only on its own
fields (seed included) — never on what it was batched with, padded to,
or preempted by. `tests/test_serving.py` holds the scheduler to
bit-identity against a solo `DiffusionInferencePipeline.generate_samples`
call with the same arguments.
"""
from __future__ import annotations

import dataclasses
import threading
from typing import Any, Dict, List, Optional

import numpy as np


class DeadlineExceeded(Exception):
    """The request was shed before compute: its deadline had already
    passed when the dispatch loop reached it."""


class SchedulerClosed(Exception):
    """Submitted after close(), or cancelled by a non-draining close."""


@dataclasses.dataclass
class SampleRequest:
    """One serving request: `num_samples` samples from one seed.

    `prompts` (optional) must have length `num_samples` when given —
    the same coupling `generate_samples` has. `conditioning` bypasses
    the encoder with a pre-encoded array. `deadline_s` is a relative
    latency budget from submit time; a request that is still queued
    when it expires is shed before any compute is spent on it.

    `cache_plan` is the per-request quality/latency knob: an
    `ops.diffcache.CachePlan` activates the training-free activation
    cache for this request's trajectory, and an
    `ops.spatialcache.ComposedPlan` (or bare `SpatialPlan`) adds the
    token-level spatial axis on top (docs/CACHING.md). None (the
    default) keeps sampling bit-identical to the uncached path. The
    plan is normalized (degenerate axes route to the simpler program)
    and then becomes part of the engine's group/program cache key, so
    requests with different effective plans never share a compiled
    program.

    `tenant` and `slo_ms` are accounting-only fields: the front door's
    SLO engine attributes the outcome (delivered within `slo_ms`?) to
    the tenant's error budget, and burn-rate brownout degrades the
    over-budget tenant first. Neither field is part of the engine group
    key, so they never change batching or compiled programs.
    """
    num_samples: int = 1
    resolution: int = 64
    diffusion_steps: int = 50           # NFE
    sampler: str = "ddim"
    guidance_scale: float = 0.0
    seed: int = 42
    prompts: Optional[List[str]] = None
    conditioning: Optional[Any] = None
    sequence_length: Optional[int] = None
    channels: int = 3
    use_ema: bool = True
    deadline_s: Optional[float] = None
    cache_plan: Optional[Any] = None    # ops.diffcache.CachePlan
    tenant: Optional[str] = None
    slo_ms: Optional[float] = None

    def __post_init__(self):
        if self.diffusion_steps < 1:
            raise ValueError("diffusion_steps must be >= 1")
        if self.prompts is not None:
            self.num_samples = len(self.prompts)
        if self.num_samples < 1:
            raise ValueError("num_samples must be >= 1")


@dataclasses.dataclass
class SampleResult:
    """Samples plus the request's latency decomposition (milliseconds).

    queue_ms   submit -> first dispatch
    compile_ms program trace+compile stalls in rounds this request
               rode (0 on a warm program cache)
    device_ms  residual: latency - queue - compile — dispatch plus
               device execution of every round to result readiness
    latency_ms submit -> samples ready on host
    rounds     scheduler rounds the request participated in
    attempts   failed dispatch attempts that were retried before this
               result (0 on the healthy path) — each retry replayed
               the trajectory bit-exactly from the request's seed
    degraded   brownout flags ("nfe_capped", "plan_forced", ...) when
               admission degraded the request instead of shedding it
               (docs/SERVING.md "Failure semantics"); empty otherwise
    """
    samples: np.ndarray
    request: SampleRequest
    queue_ms: float = 0.0
    compile_ms: float = 0.0
    device_ms: float = 0.0
    latency_ms: float = 0.0
    rounds: int = 0
    attempts: int = 0
    degraded: tuple = ()

    def timings(self) -> Dict[str, float]:
        return {"queue_ms": self.queue_ms, "compile_ms": self.compile_ms,
                "device_ms": self.device_ms, "latency_ms": self.latency_ms}


class ServingFuture:
    """Minimal thread-safe future for one request's result.

    First set wins: once resolved (result OR exception) later sets are
    ignored — the failure-isolation sweeps (dispatch-thread death,
    non-draining close, engine rebuild) may race the completion
    thread's delivery, and a delivered result must never be clobbered
    by a later blanket failure."""

    def __init__(self):
        self._lock = threading.Lock()
        self._event = threading.Event()
        self._result: Optional[SampleResult] = None
        self._exception: Optional[BaseException] = None

    def set_result(self, result: SampleResult) -> bool:
        with self._lock:
            if self._event.is_set():
                return False
            self._result = result
            self._event.set()
            return True

    def set_exception(self, exc: BaseException) -> bool:
        with self._lock:
            if self._event.is_set():
                return False
            self._exception = exc
            self._event.set()
            return True

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None) -> SampleResult:
        if not self._event.wait(timeout):
            raise TimeoutError("serving result not ready")
        if self._exception is not None:
            raise self._exception
        assert self._result is not None
        return self._result

"""Coordinated multi-host restart (resilience/coordination.py) on CPU:
step-ledger commits, two-phase commit rounds, consensus restore, and
crash-barrier timeouts — all over the in-memory transport, so every
consensus path runs single-process in tier-1. The same protocol over
REAL `jax.distributed` is covered by tests/test_multiprocess.py.
"""
import json
import threading
import time

import numpy as np
import pytest

from flaxdiff_tpu import resilience as R
from flaxdiff_tpu.trainer.checkpoints import Checkpointer


def _coordinators(n, timeout=5.0, event_log=None):
    return [R.RestartCoordinator(t, barrier_timeout=timeout,
                                 event_log=event_log)
            for t in R.InMemoryTransport.make_world(n)]


def _both(fn0, fn1):
    """Run two ranks concurrently; re-raise the first failure."""
    out, errs = [None, None], []

    def run(i, fn):
        try:
            out[i] = fn()
        except Exception as e:  # noqa: BLE001 — surfaced below
            errs.append(e)

    t = threading.Thread(target=run, args=(1, fn1))
    t.start()
    run(0, fn0)
    t.join()
    if errs:
        raise errs[0]
    return out


# -- step ledger --------------------------------------------------------------

def test_ledger_roundtrip_and_torn_tail(tmp_path):
    led = R.StepLedger(str(tmp_path))
    led.record_commit(2, world_size=4)
    led.record_commit(4, world_size=4, extra={"note": "post-resume"})
    led.record_invalidate(2, reason="operator")
    led.record_note("relaunch requested")
    assert led.committed_steps() == [4]
    assert led.is_committed(4) and not led.is_committed(2)
    # a crash mid-append leaves a torn trailing line: reads must drop
    # it (the entry never reached the ack barrier) and keep the rest
    with open(led.path, "a") as f:
        f.write('{"kind": "commit", "step": 6, "wo')
    assert R.StepLedger(str(tmp_path)).committed_steps() == [4]


def test_ledger_absent_reads_empty(tmp_path):
    led = R.StepLedger(str(tmp_path / "nowhere"))
    assert not led.exists()
    assert led.committed_steps() == []
    assert led.entries() == []


# -- transport / crash barriers ----------------------------------------------

def test_inmemory_barrier_syncs_and_times_out():
    t0, t1 = R.InMemoryTransport.make_world(2)
    assert _both(lambda: t0.barrier("b1", 5.0),
                 lambda: t1.barrier("b1", 5.0)) == [None, None]
    # a missing member turns into BarrierTimeout on the survivor,
    # within the deadline — never an indefinite hang
    start = time.monotonic()
    with pytest.raises(R.BarrierTimeout):
        t0.barrier("b2", 0.3)
    assert time.monotonic() - start < 3.0


def test_inmemory_allgather_and_broadcast():
    t0, t1 = R.InMemoryTransport.make_world(2)
    got = _both(lambda: t0.allgather_json("g", [2, 4], 5.0),
                lambda: t1.allgather_json("g", [2], 5.0))
    assert got == [[[2, 4], [2]], [[2, 4], [2]]]
    got = _both(lambda: t0.broadcast_json("d", 7, 5.0),
                lambda: t1.broadcast_json("d", None, 5.0))
    assert got == [7, 7]


# -- two-phase commit ---------------------------------------------------------

def test_commit_unanimous_writes_one_ledger_entry(tmp_path):
    ev = R.EventLog("t")
    c0, c1 = _coordinators(2, event_log=ev)
    led = R.StepLedger(str(tmp_path))
    assert _both(lambda: c0.commit(4, led),
                 lambda: c1.commit(4, led)) == [4, 4]
    assert led.committed_steps() == [4]
    # only the coordinator (rank 0) wrote; exactly one commit entry
    assert sum(e["kind"] == "commit" for e in led.entries()) == 1
    assert led.entries()[0]["world"] == 2
    assert ev.count("commit", "ckpt.commit") == 2      # both ranks record


def test_commit_aborts_on_non_unanimous_votes(tmp_path):
    ev = R.EventLog("t")
    c0, c1 = _coordinators(2, event_log=ev)
    led = R.StepLedger(str(tmp_path))
    # rank 1's save failed (votes None): the step must NOT become
    # restorable anywhere
    assert _both(lambda: c0.commit(6, led),
                 lambda: c1.commit(None, led)) == [None, None]
    assert led.committed_steps() == []
    assert ev.count("commit_aborted", "ckpt.commit") >= 1


def test_commit_all_none_is_quiet_noop(tmp_path):
    ev = R.EventLog("t")
    c0, c1 = _coordinators(2, event_log=ev)
    led = R.StepLedger(str(tmp_path))
    assert _both(lambda: c0.commit(None, led),
                 lambda: c1.commit(None, led)) == [None, None]
    assert ev.count("commit_aborted") == 0


def test_commit_timeout_marks_lost_and_later_commits_skip(tmp_path):
    ev = R.EventLog("t")
    lost = []
    c0 = R.RestartCoordinator(R.InMemoryTransport.make_world(2)[0],
                              barrier_timeout=0.3, event_log=ev,
                              on_lost=lost.append)
    led = R.StepLedger(str(tmp_path))
    # the peer is dead: the vote gather misses its deadline
    with pytest.raises(R.BarrierTimeout):
        c0.commit(4, led)
    assert c0.lost and lost          # elastic re-admission hook fired
    assert ev.count("barrier_timeout", "coord.barrier") == 1
    # once lost, commits degrade to fast local skips — the clean
    # checkpoint-and-exit path must never re-enter a hung world
    start = time.monotonic()
    assert c0.commit(6, led) is None
    assert time.monotonic() - start < 0.2
    assert ev.count("commit_skipped", "ckpt.commit") == 1
    assert led.committed_steps() == []


# -- consensus restore --------------------------------------------------------

def test_consensus_picks_max_common_step():
    c0, c1 = _coordinators(2)
    # host 1 locally lost step 4: the world agrees on 2
    assert _both(lambda: c0.consensus_restore_step([2, 4]),
                 lambda: c1.consensus_restore_step([2])) == [2, 2]


def test_consensus_cold_start_is_none():
    c0, c1 = _coordinators(2)
    assert _both(lambda: c0.consensus_restore_step([]),
                 lambda: c1.consensus_restore_step([])) == [None, None]


def test_consensus_disjoint_sets_raise_divergence():
    c0, c1 = _coordinators(2)
    errs = []

    def run(c, steps):
        try:
            c.consensus_restore_step(steps)
        except R.ConsensusError as e:
            errs.append(e)

    t = threading.Thread(target=run, args=(c1, [2]))
    t.start()
    run(c0, [4])
    t.join()
    # BOTH hosts refuse: restoring would build a divergent world
    assert len(errs) == 2


# -- ledger-aware Checkpointer ------------------------------------------------

def _save_committed(directory, steps, uncommitted=(), coordinator=None):
    """Save `steps` with commits and `uncommitted` without; distinct
    payload per step so restores are attributable."""
    if coordinator is None:
        coordinator = R.RestartCoordinator(
            R.InMemoryTransport.make_world(1)[0], barrier_timeout=5.0)
    ck = Checkpointer(str(directory), max_to_keep=8,
                      coordinator=coordinator)
    for s in steps:
        assert ck.save(s, {"w": np.full(8, float(s))})
        assert ck.commit_pending() == s
    for s in uncommitted:
        assert ck.save(s, {"w": np.full(8, float(s))})
    ck.wait_until_finished()
    return ck


def test_checkpointer_commit_and_ledger_aware_latest(tmp_path):
    ck = _save_committed(tmp_path, [2, 4], uncommitted=[5])
    assert ck.all_steps() == [2, 4, 5]
    assert ck.committed_steps() == [2, 4]
    # an on-disk step the commit round never blessed is not restorable
    assert ck.latest_step() == 4
    assert ck.locally_valid_steps() == [2, 4]
    ck.close()


def test_consensus_restore_skips_corrupt_and_uncommitted(tmp_path):
    """The acceptance story, world of one: newest committed step
    truncated, newest on-disk step uncommitted — restore lands on the
    newest step that is both committed AND intact."""
    ev = R.EventLog("t")
    ck = _save_committed(tmp_path, [2, 4], uncommitted=[5])
    ck.close()
    R.corrupt_step_dir(str(tmp_path), 4, mode="truncate")
    coord = R.RestartCoordinator(R.InMemoryTransport.make_world(1)[0],
                                 barrier_timeout=5.0, event_log=ev)
    ck2 = Checkpointer(str(tmp_path), max_to_keep=8, coordinator=coord)
    state, _ = ck2.restore({"w": np.zeros(8)})
    np.testing.assert_array_equal(np.asarray(state["w"]), np.full(8, 2.0))
    assert ev.count("consensus_restore", "ckpt.restore") == 1
    ck2.close()


def test_consensus_restore_cold_start_raises_filenotfound(tmp_path):
    coord = R.RestartCoordinator(R.InMemoryTransport.make_world(1)[0],
                                 barrier_timeout=5.0)
    ck = Checkpointer(str(tmp_path / "empty"), coordinator=coord)
    with pytest.raises(FileNotFoundError):
        ck.restore({"w": np.zeros(8)})
    ck.close()


def test_ledger_mode_fallback_never_picks_uncommitted(tmp_path):
    """use_ledger without a coordinator: the ordinary walk-back is
    restricted to COMMITTED steps (garbage corruption is only caught at
    read time, so the walk must still happen — but never into the
    uncommitted newest write)."""
    ev = R.EventLog("t")
    ck = _save_committed(tmp_path, [2, 4], uncommitted=[5])
    ck.close()
    R.corrupt_step_dir(str(tmp_path), 4)     # garbage: shallow-ok, read fails
    ck2 = Checkpointer(str(tmp_path), max_to_keep=8, use_ledger=True,
                       event_log=ev)
    assert ck2.latest_step() == 4            # listed until read fails
    with R.use_event_log(ev):
        state, _ = ck2.restore({"w": np.zeros(8)})
    np.testing.assert_array_equal(np.asarray(state["w"]), np.full(8, 2.0))
    assert ev.count("fallback_restore", "ckpt.restore") >= 1
    ck2.close()


def test_local_valid_fault_site_drops_newest(tmp_path):
    ck = _save_committed(tmp_path, [2, 4])
    plan = R.FaultPlan([R.FaultSpec("coord.local_valid", at=(1,),
                                    error="flag", times=1)])
    with plan.installed():
        assert ck.locally_valid_steps() == [2]
    assert ck.locally_valid_steps() == [2, 4]    # one-shot fault
    ck.close()


def test_commit_pending_without_ledger_is_noop(tmp_path):
    ck = Checkpointer(str(tmp_path))
    assert ck.save(3, {"w": np.zeros(4)})
    assert ck.commit_pending() == 3          # returns the step, no ledger
    ck.wait_until_finished()
    assert not R.StepLedger(str(ck.directory)).exists()
    assert ck.latest_step() == 3             # plain behavior unchanged
    ck.close()


# -- verify CLI ---------------------------------------------------------------

def test_verify_cli_all_steps_json_reports_ledger(tmp_path, capsys):
    from scripts.verify_checkpoint import main
    ck = _save_committed(tmp_path / "ck", [2], uncommitted=[4])
    ck.close()
    assert main([str(tmp_path / "ck"), "--all-steps", "--json"]) == 0
    report = json.loads(capsys.readouterr().out)
    assert report["ok"] is True
    assert report["ledger"]["present"] is True
    assert report["ledger"]["committed_steps"] == [2]
    by_step = {s["step"]: s for s in report["steps"]}
    assert by_step[2]["committed"] is True
    assert by_step[4]["committed"] is False   # on disk, never committed
    # human mode carries the same verdicts
    assert main([str(tmp_path / "ck"), "--all-steps"]) == 0
    out = capsys.readouterr().out
    assert "UNCOMMITTED" in out and "committed" in out


def test_verify_cli_no_ledger_reports_absent(tmp_path, capsys):
    from flaxdiff_tpu.trainer.checkpoints import Checkpointer as CK
    ck = CK(str(tmp_path / "ck"))
    assert ck.save(2, {"w": np.zeros(4)})
    ck.wait_until_finished()
    ck.close()
    from scripts.verify_checkpoint import main
    assert main([str(tmp_path / "ck"), "--all-steps", "--json"]) == 0
    report = json.loads(capsys.readouterr().out)
    assert report["ledger"]["present"] is False
    assert report["steps"][0]["committed"] is None


# -- trainer integration ------------------------------------------------------

def _tiny_trainer(mesh, tmp_path=None, coordinator=None, **cfg_kw):
    import flax.linen as nn
    import jax.numpy as jnp
    import optax

    from flaxdiff_tpu.predictors import EpsilonPredictionTransform
    from flaxdiff_tpu.schedulers import CosineNoiseSchedule
    from flaxdiff_tpu.trainer import DiffusionTrainer, TrainerConfig

    class Tiny(nn.Module):
        @nn.compact
        def __call__(self, x, t, cond=None):
            return nn.Conv(x.shape[-1], (3, 3))(x)

    model = Tiny()
    ck = None
    if tmp_path is not None:
        ck = Checkpointer(str(tmp_path), max_to_keep=8,
                          coordinator=coordinator)
    return DiffusionTrainer(
        apply_fn=lambda p, x, t, c: model.apply({"params": p}, x, t, None),
        init_fn=lambda key: model.init(key, jnp.zeros((1, 8, 8, 1)),
                                       jnp.zeros((1,)))["params"],
        tx=optax.adam(1e-3),
        schedule=CosineNoiseSchedule(timesteps=100),
        transform=EpsilonPredictionTransform(),
        mesh=mesh,
        config=TrainerConfig(normalize=False, log_every=2, **cfg_kw),
        checkpointer=ck)


def _data(rng, n=64):
    while True:
        yield {"sample": rng.normal(size=(8, 8, 8, 1)).astype(np.float32)}


def test_restore_at_start_resumes_and_cold_starts(mesh, tmp_path, rng):
    ev = R.EventLog("t")
    with R.use_event_log(ev):
        tr = _tiny_trainer(mesh, tmp_path / "ck", restore_at_start=True)
        tr.fit(_data(rng), total_steps=3)     # cold start: nothing on disk
        tr.checkpointer.wait_until_finished()
    assert ev.count("cold_start", "train.start") == 1
    tr.checkpointer.close()

    ev2 = R.EventLog("t2")
    with R.use_event_log(ev2):
        tr2 = _tiny_trainer(mesh, tmp_path / "ck", restore_at_start=True)
        tr2.fit(_data(rng), total_steps=2)
    import jax
    assert int(jax.device_get(tr2.state.step)) == 5   # resumed 3, ran 2
    assert ev2.count("restored", "train.start") == 1
    tr2.checkpointer.close()


def test_fit_commits_saves_into_ledger(mesh, tmp_path, rng):
    coord = R.RestartCoordinator(R.InMemoryTransport.make_world(1)[0],
                                 barrier_timeout=5.0)
    tr = _tiny_trainer(mesh, tmp_path / "ck", coordinator=coord)
    hist = tr.fit(_data(rng), total_steps=4, save_every=2)
    assert hist["coordination_lost"] is False
    ck = tr.checkpointer
    # every save fit made (2, 4) went through the commit round
    assert ck.ledger.committed_steps() == ck.all_steps()
    assert ck.latest_step() == 4
    ck.close()


def test_fit_survives_commit_barrier_timeout(mesh, tmp_path, rng):
    """Crash barrier end-to-end: the peer never votes, the commit round
    times out, and fit takes the clean checkpoint-and-exit path — the
    local save still lands on disk, uncommitted — instead of hanging."""
    ev = R.EventLog("t")
    # world of 2, but rank 1 is never driven: a dead host
    t0 = R.InMemoryTransport.make_world(2)[0]
    coord = R.RestartCoordinator(t0, barrier_timeout=0.5, event_log=ev)
    tr = _tiny_trainer(mesh, tmp_path / "ck", coordinator=coord)
    with R.use_event_log(ev):
        start = time.monotonic()
        hist = tr.fit(_data(rng), total_steps=20, save_every=2)
        elapsed = time.monotonic() - start
    assert hist["coordination_lost"] is True
    assert hist["preempted"] is True          # stopped early, cleanly
    assert elapsed < 60
    assert ev.count("barrier_timeout", "coord.barrier") >= 1
    assert ev.count("commit_skipped", "ckpt.commit") >= 1
    ck = tr.checkpointer
    ck.wait_until_finished()
    assert ck.all_steps()                     # local durability kept
    assert ck.ledger.committed_steps() == []  # but nothing committed
    ck.close()


def test_sigterm_handler_failure_warns_not_silent(mesh, rng):
    """Satellite: fit off the main thread cannot install the SIGTERM
    handler — that must surface as a resilience warning event, not a
    silent loss of preemption safety (trainer.py:344 before this PR)."""
    ev = R.EventLog("t")
    tr = _tiny_trainer(mesh, checkpoint_on_sigterm=True)
    errs = []

    def run():
        try:
            with R.use_event_log(ev):
                tr.fit(_data(np.random.default_rng(0)), total_steps=1)
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    t = threading.Thread(target=run)
    t.start()
    t.join()
    assert not errs
    assert ev.count("warning", "train.sigterm") == 1

# -- epoch-tagged vote payloads (docs/RESILIENCE.md open item) ----------------

def test_same_epoch_commit_roundtrips(tmp_path):
    """Tagging is invisible when the world shares one incarnation."""
    ev = R.EventLog("t")
    c0, c1 = [R.RestartCoordinator(t, barrier_timeout=5.0, event_log=ev,
                                   epoch=7)
              for t in R.InMemoryTransport.make_world(2)]
    led = R.StepLedger(str(tmp_path))
    assert _both(lambda: c0.commit(4, led),
                 lambda: c1.commit(4, led)) == [4, 4]
    assert led.committed_steps() == [4]


def test_stale_epoch_vote_aborts_commit(tmp_path):
    """A late voter from a PREVIOUS incarnation: its stale KV value
    survives under this round's key. The epoch tag turns what would
    have been a silently-counted vote into a clean abort — the step
    never becomes restorable."""
    ev = R.EventLog("t")
    t0, t1 = R.InMemoryTransport.make_world(2)
    # incarnation 1's rank-1 voted step 4 and died; its payload is
    # still in the store when incarnation 2's round begins
    t1._world.put("ag/commit.0/1", json.dumps({"epoch": 1, "value": 4}))
    c0 = R.RestartCoordinator(t0, barrier_timeout=5.0, event_log=ev,
                              epoch=2)
    led = R.StepLedger(str(tmp_path))
    assert c0.commit(4, led) is None
    assert led.committed_steps() == []
    aborts = ev.events("commit_aborted")
    assert aborts and "epoch" in aborts[0].detail


def test_stale_epoch_set_poisons_consensus(tmp_path):
    """Same scenario on the restore path: a stale incarnation's step
    set must raise ConsensusError, never pick the restore step."""
    t0, t1 = R.InMemoryTransport.make_world(2)
    t1._world.put("ag/restore.0/1",
                  json.dumps({"epoch": 0, "value": [2, 4]}))
    c0 = R.RestartCoordinator(t0, barrier_timeout=5.0, epoch=3)
    with pytest.raises(R.ConsensusError, match="epoch"):
        c0.consensus_restore_step([2, 4])


def test_agree_epoch_converges_divergent_incarnations(tmp_path):
    """goodput.json is written by process 0 only, so with a host-local
    telemetry dir (or a torn read) local incarnations diverge — rank 0
    at N+1, others stuck at 1. agree_epoch broadcasts rank 0's value so
    every host tags with the SAME epoch; coordinators built on the
    agreed value then commit normally, where divergent tags would have
    aborted every round forever."""
    ev = R.EventLog("t")
    transports = R.InMemoryTransport.make_world(2)
    local = [3, 1]                 # rank 1 never saw goodput.json
    agreed = _both(
        lambda: R.agree_epoch(transports[0], local[0], timeout=5.0,
                              event_log=ev),
        lambda: R.agree_epoch(transports[1], local[1], timeout=5.0,
                              event_log=ev))
    assert agreed == [3, 3]        # rank 0 is authoritative
    adopted = ev.events("epoch_adopted")
    assert len(adopted) == 1 and "1" in adopted[0].detail
    # the agreed epoch makes the world commit-capable
    c0, c1 = [R.RestartCoordinator(t, barrier_timeout=5.0, event_log=ev,
                                   epoch=e)
              for t, e in zip(transports, agreed)]
    led = R.StepLedger(str(tmp_path))
    assert _both(lambda: c0.commit(4, led),
                 lambda: c1.commit(4, led)) == [4, 4]
    assert led.committed_steps() == [4]


def test_divergent_epochs_abort_every_round(tmp_path):
    """The failure mode agree_epoch exists to prevent: coordinators
    tagged with different epochs abort every commit round."""
    ev = R.EventLog("t")
    t0, t1 = R.InMemoryTransport.make_world(2)
    c0 = R.RestartCoordinator(t0, barrier_timeout=5.0, event_log=ev,
                              epoch=2)
    c1 = R.RestartCoordinator(t1, barrier_timeout=5.0, event_log=ev,
                              epoch=1)
    led = R.StepLedger(str(tmp_path))
    assert _both(lambda: c0.commit(4, led),
                 lambda: c1.commit(4, led)) == [None, None]
    assert led.committed_steps() == []
    assert ev.count("commit_aborted", "ckpt.commit") == 2


def test_same_incarnation_step_drift_rejected_as_stale(tmp_path):
    """ISSUE 12 satellite: two drivers of the SAME incarnation drifted
    apart by a save interval (asymmetric restore, replayed rank). The
    per-step tag on commit votes turns what used to be an opaque
    non-unanimous abort into a distinct, diagnosable `commit_stale`
    rejection — and the step never becomes restorable."""
    ev = R.EventLog("t")
    c0, c1 = _coordinators(2, event_log=ev)
    led = R.StepLedger(str(tmp_path))
    got = _both(lambda: c0.commit(10, led),
                lambda: c1.commit(20, led))
    assert got == [None, None]
    assert led.committed_steps() == []
    stale = ev.events("commit_stale")
    assert len(stale) == 2 and "drift" in stale[0].detail
    assert ev.count("commit_aborted", "ckpt.commit") == 0
    # the legacy failure mode — one host's SAVE failed (vote None) at
    # the same step — still reads as the plain non-unanimous abort,
    # never mislabeled as driver drift
    got = _both(lambda: c0.commit(4, led),
                lambda: c1.commit(None, led))
    assert got == [None, None]
    assert ev.count("commit_aborted", "ckpt.commit") == 2
    assert ev.count("commit_stale", "ckpt.commit") == 2   # unchanged


def test_untagged_payload_rejected(tmp_path):
    """A foreign writer (pre-epoch binary, corrupted payload) that
    gathers as a raw value — not a tagged dict — is treated exactly
    like a stale epoch: abort, don't guess."""
    ev = R.EventLog("t")
    t0, t1 = R.InMemoryTransport.make_world(2)
    t1._world.put("ag/commit.0/1", json.dumps(4))     # untagged vote
    c0 = R.RestartCoordinator(t0, barrier_timeout=5.0, event_log=ev,
                              epoch=0)
    led = R.StepLedger(str(tmp_path))
    assert c0.commit(4, led) is None
    assert led.committed_steps() == []
    assert ev.count("commit_aborted", "ckpt.commit") == 1

"""The `Telemetry` hub: one object bundling the metrics registry,
exporters, goodput ledger, trace recorder, and cross-host aggregator —
what the trainer/data/checkpoint/inference layers actually talk to.

Two modes share one API:

- **disabled** (the process-global default): in-memory registry and
  goodput account, no exporters, no recorder. Every instrumentation
  call still works (tests read the in-memory account) but `enabled` is
  False, so the trainer skips the per-step `block_until_ready` that
  exact device-phase timing requires — zero behavior change for
  un-instrumented runs.
- **enabled** (`Telemetry.create(directory)` / train.py
  `--telemetry_dir`): JSONL stream + optional Prometheus textfile +
  optional fan-out into the run's existing loggers, Chrome trace
  recorder, persistent goodput ledger, and (given a Transport)
  pod-wide aggregation.

Layers with no plumbing (the data loader's worker threads) record on
the process-global hub (`global_telemetry()`); tests scope one with
`use_telemetry(...)` — the same pattern as `resilience.events`.

Dependency direction: telemetry imports nothing from trainer/ or
data/; the Transport it aggregates over is duck-typed (resilience's
event log is imported lazily only to record a failed round).
"""
from __future__ import annotations

import contextlib
import os
import threading
from typing import Dict, List, Optional

from .aggregate import CrossHostAggregator
from .devprof import DEVPROF_FILENAME
from .flightrec import FlightRecorder
from .goodput import GOODPUT_FILENAME, GoodputLedger
from .metrics import (JsonlExporter, LoggerExporter, MetricsRegistry,
                      PrometheusTextfileExporter)
from .phases import StepPhaseTimer
from .programs import PROGRAMS_FILENAME, ProgramRegistry
from .tracing import TraceRecorder

TELEMETRY_JSONL = "telemetry.jsonl"
TRACE_FILENAME = "trace.json"


class Telemetry:
    def __init__(self,
                 registry: Optional[MetricsRegistry] = None,
                 exporters: List = (),
                 recorder: Optional[TraceRecorder] = None,
                 goodput: Optional[GoodputLedger] = None,
                 aggregator: Optional[CrossHostAggregator] = None,
                 enabled: Optional[bool] = None,
                 epoch: Optional[int] = None,
                 programs: Optional[ProgramRegistry] = None,
                 flightrec: Optional["FlightRecorder"] = None,
                 devprof_path: Optional[str] = None):
        self.registry = registry if registry is not None else MetricsRegistry()
        self.exporters = list(exporters)
        self.recorder = recorder
        # fault flight recorder (telemetry/flightrec.py): None on the
        # disabled hub — write_record/export forward into its rings
        self.flightrec = flightrec
        # bounded-trace drop accounting must hold for EVERY hub that
        # carries a recorder, not only ones built via create(): a
        # recorder handed in bare (tests, ad-hoc front-door hubs) gets
        # the same counter wired here, so no lane can drop silently
        if recorder is not None and not recorder.has_on_drop:
            recorder.set_on_drop(
                lambda n: self.registry.counter(
                    "telemetry/trace_dropped_events").inc(n))
        self.goodput = goodput if goodput is not None else GoodputLedger()
        self.aggregator = aggregator
        # program evidence registry (telemetry/programs.py): None on
        # the disabled hub — compile sites check for it and skip
        # registration entirely, so the default path sees zero change
        self.programs = programs
        # device-profile evidence sink (telemetry/devprof.py): the
        # trainer/scheduler build a DeviceProfiler against this path
        # when profile windows are configured; None (the disabled hub)
        # keeps the profiler unbuilt — zero change off-telemetry
        self.devprof_path = devprof_path
        # every raw JSONL row is stamped with this epoch (the
        # pod-agreed job incarnation — see set_epoch); defaults to the
        # local goodput incarnation so even a solo host's rows are
        # distinguishable across restarts
        self.epoch = int(epoch) if epoch is not None \
            else int(self.goodput.incarnation)
        # enabled gates the COSTLY instrumentation (per-step device sync,
        # per-step JSONL rows); cheap counters/spans run regardless
        self.enabled = bool(enabled) if enabled is not None \
            else bool(self.exporters or self.recorder)

    @classmethod
    def create(cls, directory: str,
               transport=None,
               prometheus_textfile: Optional[str] = None,
               logger=None,
               process_index: Optional[int] = None) -> "Telemetry":
        """Fully-enabled hub rooted at `directory`. Per-host files get a
        `_p<rank>` suffix beyond rank 0 so a shared directory never
        interleaves hosts; the goodput account is job-level (process 0
        writes, everyone records)."""
        pid = process_index
        if pid is None:
            pid = transport.process_index if transport is not None else 0
        os.makedirs(directory, exist_ok=True)
        suffix = "" if pid == 0 else f"_p{pid}"

        def _in_dir(name: str) -> str:
            stem, ext = os.path.splitext(name)
            return os.path.join(directory, stem + suffix + ext)

        exporters: List = [JsonlExporter(_in_dir(TELEMETRY_JSONL))]
        if prometheus_textfile:
            exporters.append(PrometheusTextfileExporter(prometheus_textfile))
        if logger is not None:
            exporters.append(LoggerExporter(logger))
        registry = MetricsRegistry()
        # fault flight recorder: rings fed by write_record/export below,
        # resilience events via the CURRENT global event log (tests
        # that scope a log with use_event_log attach their own)
        flightrec = FlightRecorder(directory, registry=registry)
        from ..resilience.events import global_event_log
        flightrec.attach_events(global_event_log())
        return cls(
            registry=registry,
            exporters=exporters,
            # bounded-event drops surface as a counter, not only as the
            # saved file's flaxdiff_dropped_events field — a trace that
            # silently degraded must be visible in the metrics stream
            recorder=TraceRecorder(
                _in_dir(TRACE_FILENAME), pid=pid,
                on_drop=lambda n: registry.counter(
                    "telemetry/trace_dropped_events").inc(n)),
            goodput=GoodputLedger(os.path.join(directory, GOODPUT_FILENAME),
                                  process_index=pid),
            aggregator=(CrossHostAggregator(transport)
                        if transport is not None else None),
            programs=ProgramRegistry(_in_dir(PROGRAMS_FILENAME),
                                     registry=registry),
            flightrec=flightrec,
            devprof_path=_in_dir(DEVPROF_FILENAME),
            enabled=True)

    # -- instruments ---------------------------------------------------------
    def counter(self, name: str):
        return self.registry.counter(name)

    def gauge(self, name: str):
        return self.registry.gauge(name)

    def histogram(self, name: str, **kwargs):
        return self.registry.histogram(name, **kwargs)

    def step_timer(self, mfu_meter=None,
                   sample_every: int = 1) -> StepPhaseTimer:
        return StepPhaseTimer(registry=self.registry, mfu_meter=mfu_meter,
                              sample_every=sample_every)

    # -- tracing -------------------------------------------------------------
    def span(self, name: str, cat: str = "run",
             args: Optional[Dict[str, object]] = None):
        if self.recorder is None:
            return contextlib.nullcontext()
        return self.recorder.span(name, cat=cat, args=args)

    def instant(self, name: str, cat: str = "event",
                args: Optional[Dict[str, object]] = None) -> None:
        if self.recorder is not None:
            self.recorder.instant(name, cat=cat, args=args)

    def set_epoch(self, epoch: int) -> None:
        """Adopt the pod-agreed epoch (train.py calls this with the
        `agree_epoch` result). Every subsequent raw row carries it, so
        two drivers of the SAME incarnation that drifted apart — a
        stale process still writing after a coordinated restart voted a
        new epoch — are distinguishable row by row, not just file by
        file (the PR-3 carried-over follow-up)."""
        self.epoch = int(epoch)

    # -- export --------------------------------------------------------------
    def write_record(self, record: Dict[str, object]) -> None:
        """One raw typed record into the JSONL stream (a no-op on the
        disabled hub, which has no exporters), stamped with the current
        epoch tag unless the caller already set one."""
        if "epoch" not in record:
            record = {**record, "epoch": self.epoch}
        if self.flightrec is not None:
            self.flightrec.record(record)
        for ex in self.exporters:
            ex.write(record)

    def record_step(self, phases: Dict[str, float]) -> None:
        """One per-step phase row into the raw JSONL stream."""
        rec = {"type": "step_phases",
               "step": int(phases.get("step", -1))}
        rec.update({k: v for k, v in phases.items() if k != "step"})
        self.write_record(rec)

    def record_numerics(self, flat_aux: Dict[str, float],
                        step: Optional[int] = None) -> None:
        """One per-cadence training-health row (`type: "numerics"`) into
        the raw stream, and the global/summary series into registry
        gauges so the Prometheus textfile carries the latest values.
        Per-module series stay JSONL-only — module count times four
        stats would chew the registry's series budget on big models."""
        rec: Dict[str, object] = {"type": "numerics"}
        if step is not None:
            rec["step"] = int(step)
        rec.update(flat_aux)
        self.write_record(rec)
        for name, v in flat_aux.items():
            if not name.startswith("numerics/module/"):
                self.registry.gauge(name).set(v)

    def export(self, step: Optional[int] = None,
               extra: Optional[Dict[str, float]] = None) -> None:
        """Registry + goodput snapshot through every exporter, epoch-
        stamped like the raw rows (snapshots bypass write_record)."""
        snap = self.registry.snapshot()
        snap.update(self.goodput.snapshot())
        if extra:
            snap.update(extra)
        snap.setdefault("epoch", float(self.epoch))
        if self.flightrec is not None:
            self.flightrec.metrics(snap, step=step)
        for ex in self.exporters:
            ex.export(snap, step=step)

    def _goodput_contribution(self) -> Dict[str, float]:
        """THIS host's goodput account as aggregatable scalars. The
        persisted `goodput.json` is process 0's clock alone (it is the
        only writer); gathering every host's in-memory counters is what
        makes `pod/goodput/*` a pod-level fact — the spread of
        productive seconds across hosts IS the straggler/stall skew the
        persisted account cannot show."""
        prod, bad = self.goodput.raw_counters()
        out = {"goodput/productive_s": prod}
        for bucket, v in bad.items():
            out[f"goodput/badput/{bucket}_s"] = v
        total = prod + sum(bad.values())
        if total > 0:
            out["goodput/fraction"] = prod / total
        return out

    def aggregate(self, metrics: Dict[str, float],
                  step: Optional[int] = None
                  ) -> Optional[Dict[str, Dict[str, float]]]:
        """Pod-wide reduction of this host's metrics — merged with this
        host's goodput counters, so the pod report carries
        `pod/goodput/*` rows (no longer proc-0's clock alone). Rank 0
        writes the flattened stats as a `pod_metrics` JSONL record AND
        mirrors them into registry gauges, so the Prometheus textfile
        exposes `pod/<metric>/<stat>` for alerting
        (examples/alerting.rules.yml). ANY failed round (timed-out
        gather on a dead peer, malformed payload, transport error)
        disables further aggregation for this hub and records a
        `telemetry_lost` resilience event — metrics must never kill a
        run, so nothing is re-raised. The disabled aggregator keeps
        publishing a non-blocking tombstone each round (see
        CrossHostAggregator), so peers disable on their next gather
        instead of stalling a full timeout per log cadence."""
        if self.aggregator is None:
            return None
        contribution = dict(metrics)
        contribution.update(self._goodput_contribution())
        try:
            stats = self.aggregator.aggregate(contribution)
        except Exception as e:  # noqa: BLE001 — degrade, never die
            from ..resilience.events import record_event
            record_event("telemetry_lost", "telemetry.aggregate",
                         detail=f"{type(e).__name__}: {e}", step=step)
            return None
        if stats is None:       # disabled earlier: tombstone offered,
            return None         # event already recorded — stay quiet
        if self.aggregator.process_index == 0:
            flat = CrossHostAggregator.flatten(stats)
            rec: Dict[str, object] = {"type": "pod_metrics",
                                      "world": self.aggregator.world_size}
            if step is not None:
                rec["step"] = int(step)
            rec.update(flat)
            self.write_record(rec)
            for name, v in flat.items():
                self.registry.gauge(name).set(v)
        return stats

    # -- lifecycle -----------------------------------------------------------
    def flush(self) -> None:
        if self.recorder is not None:
            self.recorder.save()
        self.goodput.persist()

    def close(self) -> None:
        self.flush()
        if self.flightrec is not None:
            self.flightrec.close()
        for ex in self.exporters:
            ex.close()


# Process-global default hub (disabled): layers without plumbing record
# here; tests swap it via use_telemetry.
_GLOBAL = Telemetry(enabled=False)
_global_lock = threading.Lock()


def global_telemetry() -> Telemetry:
    return _GLOBAL


def set_global_telemetry(hub: Telemetry) -> Telemetry:
    """Replace the process-global hub; returns the previous one."""
    global _GLOBAL
    with _global_lock:
        prev, _GLOBAL = _GLOBAL, hub
    return prev


class use_telemetry:
    """Context manager: swap the global hub for a scope (tests)."""

    def __init__(self, hub: Telemetry):
        self._hub = hub
        self._prev: Optional[Telemetry] = None

    def __enter__(self) -> Telemetry:
        self._prev = set_global_telemetry(self._hub)
        return self._hub

    def __exit__(self, *exc):
        assert self._prev is not None
        set_global_telemetry(self._prev)
        return False

"""Flat-parameter training mode (TrainerConfig.flat_params).

Params/EMA/optimizer state live as one padded vector per dtype; the
model unflattens inside the loss so AD returns flat gradients, and
every optimizer/EMA/apply update is a fused per-dtype kernel
(trainer/optim.py module docstring; the r3 on-chip trace attributed
~12% of the train step to leaf-wise update launches). The math must be
IDENTICAL to the structured path — adam/adamw/global-norm clip are
elementwise or concatenation-invariant.
"""
import jax
import json
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from flaxdiff_tpu.models.unet import Unet
from flaxdiff_tpu.parallel import create_mesh
from flaxdiff_tpu.predictors import EpsilonPredictionTransform
from flaxdiff_tpu.schedulers import CosineNoiseSchedule
from flaxdiff_tpu.trainer import DiffusionTrainer, TrainerConfig


def _make_trainer(flat: bool, mesh_axes=None, seed=3):
    size = 8
    model = Unet(output_channels=1, emb_features=16,
                 feature_depths=(8, 16), attention_configs=(None, None),
                 num_res_blocks=1, norm_groups=4)

    def apply_fn(params, x, t, cond):
        return model.apply({"params": params}, x, t, None)

    def init_fn(key):
        return model.init(key, jnp.zeros((1, size, size, 1)),
                          jnp.zeros((1,)), None)["params"]

    return DiffusionTrainer(
        apply_fn=apply_fn, init_fn=init_fn,
        tx=optax.chain(optax.clip_by_global_norm(1.0), optax.adamw(1e-3)),
        schedule=CosineNoiseSchedule(timesteps=100),
        transform=EpsilonPredictionTransform(),
        mesh=create_mesh(axes=mesh_axes or {"data": -1}),
        config=TrainerConfig(log_every=1, uncond_prob=0.0, seed=seed,
                             flat_params=flat),
    ), size


def _batches(size, n=4, batch=8):
    rng = np.random.default_rng(0)
    return [{"sample": rng.integers(0, 255, (batch, size, size, 1))
             .astype(np.uint8)} for _ in range(n)]


def test_flat_params_matches_structured_path():
    """Same seeds, same batches: the flat-state trainer must follow the
    structured trainer's loss trajectory, params, and EMA. Tolerance is
    loose-float, not bitwise: clip_by_global_norm sums squares in a
    different order over one concatenated vector than over per-leaf
    partials, so the clip scale differs in the last ulp."""
    t_ref, size = _make_trainer(flat=False)
    t_flat, _ = _make_trainer(flat=True)
    for b in _batches(size):
        l_ref = float(t_ref.train_step(t_ref.put_batch(b)))
        l_flat = float(t_flat.train_step(t_flat.put_batch(b)))
        assert np.isclose(l_ref, l_flat, rtol=1e-6), (l_ref, l_flat)

    p_ref = jax.device_get(t_ref.get_params(use_ema=False))
    p_flat = jax.device_get(t_flat.get_params(use_ema=False))
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6),
        p_ref, p_flat)
    e_ref = jax.device_get(t_ref.get_params(use_ema=True))
    e_flat = jax.device_get(t_flat.get_params(use_ema=True))
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6),
        e_ref, e_flat)


def test_flat_params_state_is_flat_and_fsdp_sharded():
    """The state really is per-dtype vectors (that is the entire point:
    a handful of big leaves instead of hundreds), padded to 1024 so any
    fsdp axis divides it; under a (data, fsdp) mesh the vectors shard.
    The model must clear infer_fsdp_spec's 64k min_size (tiny tensors
    are deliberately replicated), so this test uses a ~119k-param
    config."""
    size = 8
    model = Unet(output_channels=1, emb_features=32,
                 feature_depths=(16, 32), attention_configs=(None, None),
                 num_res_blocks=1, norm_groups=4)

    def apply_fn(params, x, t, cond):
        return model.apply({"params": params}, x, t, None)

    def init_fn(key):
        return model.init(key, jnp.zeros((1, size, size, 1)),
                          jnp.zeros((1,)), None)["params"]

    t_flat = DiffusionTrainer(
        apply_fn=apply_fn, init_fn=init_fn, tx=optax.adamw(1e-3),
        schedule=CosineNoiseSchedule(timesteps=100),
        transform=EpsilonPredictionTransform(),
        mesh=create_mesh(axes={"data": 2, "fsdp": 4}),
        config=TrainerConfig(log_every=1, uncond_prob=0.0,
                             flat_params=True))
    leaves = jax.tree_util.tree_leaves(t_flat.state.params)
    assert all(leaf.ndim == 1 for leaf in leaves)
    assert all(leaf.size % 1024 == 0 for leaf in leaves)
    # far fewer state leaves than the structured tree has params
    assert len(leaves) <= 4
    specs = jax.tree_util.tree_leaves(t_flat.state_specs.params)
    assert any("fsdp" in str(s) for s in specs)
    loss = float(t_flat.train_step(t_flat.put_batch(_batches(size, n=1)[0])))
    assert np.isfinite(loss)


def test_flat_params_trains_under_fsdp_mesh():
    t_flat, size = _make_trainer(flat=True, mesh_axes={"data": 2, "fsdp": 4})
    losses = [float(t_flat.train_step(t_flat.put_batch(b)))
              for b in _batches(size, n=3)]
    assert all(np.isfinite(losses))


def test_flat_params_sampler_roundtrip():
    """get_params returns the structured tree the samplers expect."""
    from flaxdiff_tpu.samplers import DDIMSampler, DiffusionSampler
    from flaxdiff_tpu.utils import RngSeq

    t_flat, size = _make_trainer(flat=True)
    for b in _batches(size, n=2):
        t_flat.train_step(t_flat.put_batch(b))
    engine = DiffusionSampler(
        model_fn=t_flat._apply_fn,
        schedule=CosineNoiseSchedule(timesteps=100),
        transform=EpsilonPredictionTransform(),
        sampler=DDIMSampler())
    out = engine.generate_samples(
        t_flat.get_params(use_ema=False), num_samples=2, resolution=size,
        diffusion_steps=4, rngstate=RngSeq.create(0), channels=1)
    assert out.shape == (2, size, size, 1)
    assert bool(jnp.all(jnp.isfinite(out)))


def test_flat_params_with_grad_accum():
    """optax.MultiSteps over the flat vectors (CLI --grad_accum path):
    accumulation is per-leaf elementwise, so it composes with flat
    state; k micro-steps per optimizer update must still train."""
    size = 8
    model = Unet(output_channels=1, emb_features=16,
                 feature_depths=(8, 16), attention_configs=(None, None),
                 num_res_blocks=1, norm_groups=4)

    def apply_fn(params, x, t, cond):
        return model.apply({"params": params}, x, t, None)

    def init_fn(key):
        return model.init(key, jnp.zeros((1, size, size, 1)),
                          jnp.zeros((1,)), None)["params"]

    trainer = DiffusionTrainer(
        apply_fn=apply_fn, init_fn=init_fn,
        tx=optax.MultiSteps(optax.adamw(1e-3), every_k_schedule=2),
        schedule=CosineNoiseSchedule(timesteps=100),
        transform=EpsilonPredictionTransform(),
        mesh=create_mesh(axes={"data": -1}),
        config=TrainerConfig(log_every=1, uncond_prob=0.0,
                             flat_params=True))
    losses = [float(trainer.train_step(trainer.put_batch(b)))
              for b in _batches(size, n=4)]
    assert all(np.isfinite(losses))


def test_template_serialization_roundtrip():
    """param_template -> serialize -> deserialize -> unflatten must
    reproduce the original tree exactly (this is the path a flat-params
    checkpoint takes through inference restore), including nested
    modules, mixed dtypes, and pad_to padding."""
    from flaxdiff_tpu.trainer.optim import (deserialize_template,
                                            flatten_params,
                                            param_template,
                                            serialize_template,
                                            unflatten_params)

    rng = np.random.default_rng(0)
    tree = {
        "block_a": {"conv": {"kernel": rng.normal(size=(3, 3, 4, 8))
                             .astype(np.float32),
                             "bias": rng.normal(size=(8,))
                             .astype(np.float32)},
                    "scale": rng.normal(size=(13,)).astype(np.float16)},
        "head": {"kernel": rng.normal(size=(8, 2)).astype(np.float32)},
    }
    flats = flatten_params(tree, 1024)
    entries = json.loads(json.dumps(
        serialize_template(param_template(tree))))
    rebuilt = unflatten_params(deserialize_template(entries), flats)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)), tree, rebuilt)
    # dtype preserved through the JSON hop
    assert rebuilt["block_a"]["scale"].dtype == jnp.float16

#!/usr/bin/env python
"""Latent diffusion end-to-end on the first-party KL VAE.

The reference could only do latent diffusion through the downloaded
Stable-Diffusion VAE (its own autoencoder stub returned zeros and its VAE
trainer was broken). Here the whole loop is first-party: (1) train the
KL autoencoder, (2) measure the latent scaling factor (SD convention:
1/std of encoded latents), (3) train a diffusion prior in latent space —
the VAE encode runs inside the jitted train step — and (4) sample,
decoding latents back to pixels inside the sampler's post-process.
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--vae_steps", type=int, default=300)
    ap.add_argument("--steps", type=int, default=300)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--image_size", type=int, default=16)
    ap.add_argument("--smoke", action="store_true")
    args = ap.parse_args(argv)
    if args.smoke:
        args.vae_steps, args.steps, args.batch = 40, 25, 8

    import os as _os

    import jax

    if _os.environ.get("JAX_PLATFORMS"):
        # a site hook may have latched a tunneled-TPU platform at interpreter
        # startup; honor the env var (same workaround as tests/conftest.py)
        jax.config.update("jax_platforms", _os.environ["JAX_PLATFORMS"])
    import jax.numpy as jnp
    import optax

    from flaxdiff_tpu.data import get_dataset, get_dataset_grain
    from flaxdiff_tpu.models.autoencoder import KLAutoEncoder
    from flaxdiff_tpu.models.unet import Unet
    from flaxdiff_tpu.parallel import create_mesh
    from flaxdiff_tpu.predictors import EpsilonPredictionTransform
    from flaxdiff_tpu.samplers import DDIMSampler, DiffusionSampler
    from flaxdiff_tpu.schedulers import CosineNoiseSchedule
    from flaxdiff_tpu.trainer import DiffusionTrainer, TrainerConfig
    from flaxdiff_tpu.trainer.autoencoder_trainer import (
        AutoEncoderTrainer, AutoEncoderTrainerConfig)

    mesh = create_mesh(axes={"data": -1})
    dataset = get_dataset("synthetic", image_size=args.image_size, n=256)

    def batches():
        return get_dataset_grain(dataset, batch_size=args.batch,
                                 image_size=args.image_size)["train"]()

    # 1. train the VAE (2x downscale, tiny widths for the demo)
    vae0 = KLAutoEncoder.create(
        jax.random.PRNGKey(0), input_channels=3, image_size=args.image_size,
        latent_channels=4, block_channels=(16, 32), layers_per_block=1,
        norm_groups=4)
    vt = AutoEncoderTrainer(
        vae0, optax.adam(2e-3), mesh,
        AutoEncoderTrainerConfig(kl_weight=1e-6,
                                 log_every=max(args.vae_steps // 3, 1)))
    vh = vt.fit(batches(), total_steps=args.vae_steps)
    quality = vt.evaluate(next(batches()))
    print(f"VAE: recon {vh['recon'][-1]:.4f}, psnr {quality['psnr']:.1f} dB")

    # 2. latent scale so the prior sees ~unit-variance latents
    scale = vt.measure_latent_scale(batches())
    vae = vt.trained_vae(scaling_factor=scale)
    print(f"latent scaling_factor {scale:.3f} "
          f"(downscale {vae.downscale_factor}x, {vae.latent_channels}ch)")

    # 3. diffusion prior over latents: the trainer's autoencoder hook
    # encodes batches INSIDE the jitted step
    lat_res = args.image_size // vae.downscale_factor
    model = Unet(output_channels=vae.latent_channels, emb_features=64,
                 feature_depths=(32, 64), attention_configs=None,
                 num_res_blocks=1)

    def apply_fn(params, x, t, cond):
        return model.apply({"params": params}, x, t, None)

    def init_fn(key):
        return model.init(key, jnp.zeros((1, lat_res, lat_res,
                                          vae.latent_channels)),
                          jnp.zeros((1,)))["params"]

    schedule = CosineNoiseSchedule(timesteps=1000)
    transform = EpsilonPredictionTransform()
    trainer = DiffusionTrainer(
        apply_fn=apply_fn, init_fn=init_fn, tx=optax.adam(2e-3),
        schedule=schedule, transform=transform, mesh=mesh,
        config=TrainerConfig(uncond_prob=0.0,
                             log_every=max(args.steps // 3, 1)),
        autoencoder=vae)
    history = trainer.fit(batches(), total_steps=args.steps)
    print(f"prior final loss {history['final_loss']:.4f}")

    # 4. sample in latent space; the engine decodes through the VAE
    engine = DiffusionSampler(model_fn=apply_fn, schedule=schedule,
                              transform=transform, sampler=DDIMSampler(),
                              autoencoder=vae)
    samples = engine.generate_samples(
        trainer.get_params(), num_samples=4, resolution=args.image_size,
        diffusion_steps=20)
    assert samples.shape == (4, args.image_size, args.image_size, 3)
    print(f"decoded samples {samples.shape}")
    return history


if __name__ == "__main__":
    main()

"""Property tests for scheduler math (reference has none — SURVEY.md §4)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flaxdiff_tpu.schedulers import (
    CosineContinuousNoiseSchedule,
    CosineGeneralNoiseSchedule,
    CosineNoiseSchedule,
    EDMNoiseSchedule,
    ExpNoiseSchedule,
    KarrasVENoiseSchedule,
    LinearNoiseSchedule,
    SimpleExpNoiseSchedule,
    SqrtContinuousNoiseSchedule,
    get_schedule,
)

ALL_SCHEDULES = [
    LinearNoiseSchedule, CosineNoiseSchedule, ExpNoiseSchedule,
    CosineContinuousNoiseSchedule, SqrtContinuousNoiseSchedule,
    KarrasVENoiseSchedule, SimpleExpNoiseSchedule, EDMNoiseSchedule,
    CosineGeneralNoiseSchedule,
]


@pytest.mark.parametrize("cls", ALL_SCHEDULES)
def test_max_noise_std_is_marginal_std(cls):
    """max_noise_std scales initial sampling noise: it must be the x_T
    marginal std sigma(T) — ~1 for VP schedules, sigma_max for VE — never
    sigma/signal, which explodes as signal -> 0 at the VP tail."""
    s = cls(timesteps=1000)
    std = float(s.max_noise_std())
    _, sigma_T = s.rates(jnp.asarray([float(s.timesteps - 1)]))
    np.testing.assert_allclose(std, float(sigma_T[0]), rtol=1e-2)
    if not s.is_continuous or not hasattr(s, "sigma_max"):
        assert std <= 1.5, f"VP max_noise_std should be ~1, got {std}"


@pytest.mark.parametrize("cls", ALL_SCHEDULES)
def test_add_remove_noise_roundtrip(cls):
    s = cls(timesteps=100)
    key = jax.random.PRNGKey(0)
    x0 = jax.random.normal(key, (4, 8, 8, 3))
    noise = jax.random.normal(jax.random.fold_in(key, 1), (4, 8, 8, 3))
    t = s.sample_timesteps(jax.random.fold_in(key, 2), 4)
    x_t = s.add_noise(x0, noise, t)
    rec = s.remove_all_noise(x_t, noise, t)
    np.testing.assert_allclose(rec, x0, rtol=1e-3, atol=1e-3)


@pytest.mark.parametrize("cls", ALL_SCHEDULES)
def test_rates_shapes_and_positive(cls):
    s = cls(timesteps=50)
    t = s.sample_timesteps(jax.random.PRNGKey(0), 16)
    signal, sigma = s.rates(t)
    assert signal.shape == (16,) and sigma.shape == (16,)
    assert bool(jnp.all(signal > 0)) and bool(jnp.all(sigma >= 0))
    w = s.loss_weights(t)
    assert w.shape == (16,) and bool(jnp.all(jnp.isfinite(w)))


def test_discrete_vp_invariant():
    """VP property: signal^2 + noise^2 == 1 for beta-based schedules."""
    for cls in [LinearNoiseSchedule, CosineNoiseSchedule, ExpNoiseSchedule]:
        s = cls(timesteps=1000)
        t = jnp.arange(1000)
        signal, sigma = s.rates(t)
        np.testing.assert_allclose(signal**2 + sigma**2, 1.0, atol=1e-5)


def test_linear_betas_match_closed_form():
    s = LinearNoiseSchedule(timesteps=1000)
    betas = np.linspace(1e-4, 0.02, 1000)
    alphas_cumprod = np.cumprod(1 - betas)
    np.testing.assert_allclose(s.alphas_cumprod, alphas_cumprod, rtol=1e-5)


def test_cosine_alpha_bar_closed_form():
    s = CosineNoiseSchedule(timesteps=1000)
    ts = np.arange(1, 1001) / 1000
    sref = 0.008
    ab = (np.cos((ts + sref) / (1 + sref) * np.pi / 2) ** 2
          / np.cos(sref / (1 + sref) * np.pi / 2) ** 2)
    # beta clipping at 0.999 makes the tail deviate; check the first 90%.
    np.testing.assert_allclose(s.alphas_cumprod[:900], ab[:900], rtol=2e-2)


def test_karras_sigma_ramp_monotone_and_inverse():
    s = KarrasVENoiseSchedule(timesteps=40, sigma_min=0.002, sigma_max=80.0)
    t = jnp.arange(40, dtype=jnp.float32)
    sigmas = s.sigmas(t)
    # Framework-wide convention: t ascending == more noise (VP and VE alike).
    assert float(sigmas[0]) == pytest.approx(0.002, rel=1e-4)
    assert float(sigmas[-1]) == pytest.approx(80.0, rel=1e-4)
    assert bool(jnp.all(jnp.diff(sigmas) > 0))
    t_rec = s.timesteps_from_sigmas(sigmas)
    np.testing.assert_allclose(t_rec, t, atol=1e-2)


def test_edm_training_sigma_distribution():
    s = EDMNoiseSchedule(timesteps=100)
    t = s.sample_timesteps(jax.random.PRNGKey(0), 20000)
    sigma = s.sigmas(t)
    log_sigma = jnp.log(sigma)
    # ln(sigma) ~ N(-1.2, 1.2) modulo clipping at the ramp edges
    assert abs(float(jnp.median(log_sigma)) - (-1.2)) < 0.1


def test_posterior_matches_ddpm_closed_form():
    s = LinearNoiseSchedule(timesteps=100)
    betas = np.array(s.betas)
    ab = np.array(s.alphas_cumprod)
    ab_prev = np.append(1.0, ab[:-1])
    t = jnp.asarray([50])
    x0 = jnp.ones((1, 4, 4, 1))
    x_t = 0.5 * jnp.ones((1, 4, 4, 1))
    mean = s.posterior_mean(x0, x_t, t)
    c1 = betas[50] * np.sqrt(ab_prev[50]) / (1 - ab[50])
    c2 = (1 - ab_prev[50]) * np.sqrt(1 - betas[50]) / (1 - ab[50])
    np.testing.assert_allclose(mean, c1 * 1.0 + c2 * 0.5, rtol=1e-5)


def test_registry():
    for name in ["linear", "cosine", "exp", "karras", "edm", "sqrt",
                 "cosine_continuous", "cosine_general", "simple_exp"]:
        s = get_schedule(name, timesteps=10)
        assert s.timesteps == 10


def test_schedule_is_scan_carryable():
    """Schedules are pytrees: usable as lax.scan carry / jit closure."""
    s = CosineNoiseSchedule(timesteps=10)

    @jax.jit
    def f(s, x, t):
        return s.add_noise(x, jnp.zeros_like(x), t)

    out = f(s, jnp.ones((2, 4, 4, 1)), jnp.asarray([0, 5]))
    assert out.shape == (2, 4, 4, 1)

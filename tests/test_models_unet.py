"""UNet + layer forward tests (shapes, dtypes, grad flow)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flaxdiff_tpu.models import Unet
from flaxdiff_tpu.models.attention import AttentionLayer, TransformerBlock
from flaxdiff_tpu.models.common import (
    FourierEmbedding,
    PixelShuffle,
    ResidualBlock,
    TimeEmbedding,
)


def test_time_embedding_shapes():
    emb = TimeEmbedding(features=64)
    out = emb.apply({}, jnp.arange(4.0))
    assert out.shape == (4, 64)
    f = FourierEmbedding(features=64)
    params = f.init(jax.random.PRNGKey(0), jnp.arange(4.0))
    out = f.apply(params, jnp.arange(4.0))
    assert out.shape == (4, 64)


def test_pixel_shuffle():
    x = jnp.arange(2 * 2 * 2 * 8, dtype=jnp.float32).reshape(2, 2, 2, 8)
    out = PixelShuffle(scale=2)(x)
    assert out.shape == (2, 4, 4, 2)


def test_residual_block_shapes():
    block = ResidualBlock(features=32, norm_groups=8)
    x = jnp.ones((2, 8, 8, 16))
    temb = jnp.ones((2, 64))
    params = block.init(jax.random.PRNGKey(0), x, temb)
    out = block.apply(params, x, temb)
    assert out.shape == (2, 8, 8, 32)


def test_attention_self_and_cross():
    attn = AttentionLayer(heads=2, dim_head=8)
    x = jnp.ones((2, 16, 32))
    ctx = jnp.ones((2, 7, 32))
    params = attn.init(jax.random.PRNGKey(0), x, ctx)
    out = attn.apply(params, x, ctx)
    assert out.shape == (2, 16, 32)
    # spatial input auto-flattens
    xs = jnp.ones((2, 4, 4, 32))
    params = attn.init(jax.random.PRNGKey(0), xs)
    assert attn.apply(params, xs).shape == (2, 4, 4, 32)


def test_transformer_block_projection_residual():
    tb = TransformerBlock(heads=2, dim_head=16, use_projection=True)
    x = jnp.ones((2, 4, 4, 32))
    ctx = jnp.ones((2, 7, 32))
    params = tb.init(jax.random.PRNGKey(0), x, ctx)
    out = tb.apply(params, x, ctx)
    assert out.shape == x.shape
    # zero-init proj_out => output == residual at init
    np.testing.assert_allclose(out, x, atol=1e-5)


@pytest.mark.parametrize("attn", [False, True])
def test_unet_forward(attn):
    configs = None
    if attn:
        configs = [None, None, {"heads": 2, "dim_head": 16, "use_projection": True}]
    model = Unet(output_channels=3, emb_features=64,
                 feature_depths=(16, 24, 32), attention_configs=configs,
                 num_res_blocks=1, norm_groups=8)
    x = jnp.ones((2, 16, 16, 3))
    temb = jnp.asarray([0.1, 0.7])
    ctx = jnp.ones((2, 7, 32)) if attn else None
    params = model.init(jax.random.PRNGKey(0), x, temb, ctx)
    out = model.apply(params, x, temb, ctx)
    assert out.shape == (2, 16, 16, 3)
    assert out.dtype == jnp.float32
    # zero-init output conv => exactly zero output at init
    np.testing.assert_allclose(out, 0.0, atol=1e-6)


def test_unet_grad_flows():
    model = Unet(output_channels=1, emb_features=32, feature_depths=(8, 12),
                 num_res_blocks=1, norm_groups=4)
    x = jax.random.normal(jax.random.PRNGKey(1), (1, 8, 8, 1))
    temb = jnp.asarray([0.5])
    params = model.init(jax.random.PRNGKey(0), x, temb)

    target = jax.random.normal(jax.random.PRNGKey(2), x.shape)

    def loss(p):
        return jnp.mean((model.apply(p, x, temb) - target) ** 2)

    # At exact init the zero-init output conv blocks upstream gradients (the
    # standard zero-init property: only the final conv trains on step 0).
    g0 = jax.grad(loss)(params)
    norms0 = [float(jnp.abs(v).sum()) for v in jax.tree_util.tree_leaves(g0)]
    assert np.isfinite(norms0).all()
    assert sum(n > 0 for n in norms0) >= 2  # conv_out kernel + bias

    # After a couple of SGD steps every zero-init layer (output conv, then
    # each resblock's conv2) is nonzero and gradient flows everywhere.
    p = params
    for _ in range(2):
        g = jax.grad(loss)(p)
        p = jax.tree_util.tree_map(lambda w, gw: w - 0.1 * gw, p, g)
    g1 = jax.grad(loss)(p)
    norms1 = [float(jnp.abs(v).sum()) for v in jax.tree_util.tree_leaves(g1)]
    assert np.isfinite(norms1).all()
    assert sum(n > 0 for n in norms1) > len(norms1) * 2 // 3


def test_unet_bf16_compute():
    model = Unet(output_channels=3, emb_features=32, feature_depths=(8, 12),
                 num_res_blocks=1, norm_groups=4, dtype=jnp.bfloat16)
    x = jnp.ones((1, 8, 8, 3))
    temb = jnp.asarray([0.5])
    params = model.init(jax.random.PRNGKey(0), x, temb)
    out = model.apply(params, x, temb)
    assert out.shape == (1, 8, 8, 3)
    assert bool(jnp.all(jnp.isfinite(out)))

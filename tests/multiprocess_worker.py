"""Worker for the REAL 2-process `jax.distributed` end-to-end test.

Launched by tests/test_multiprocess.py, twice per phase (process_id 0/1),
each process owning 4 virtual CPU devices of a shared 8-device world.
Exercises exactly the process-boundary code that single-process mesh
simulation cannot (VERDICT r2 weak #4; reference multi-host path:
simple_trainer.py:43-65, dataloaders.py:297-305):

  grain ShardByJaxProcess per-process data sharding
    -> put_batch / jax.make_array_from_process_local_data global assembly
    -> FSDP train steps over a ("data", "fsdp") mesh (cross-process
       collectives ride gloo on CPU)
    -> orbax sharded checkpoint save with every process participating
  then, in a FRESH 2-process run:
    -> sharded restore onto the same topology + one more step.

Prints one JSON line ("RESULT {...}") with the per-step losses; the
driver asserts both processes report identical losses (the global step
is one program — divergence means broken global assembly or collectives).
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_trainer(ckpt_dir):
    import flax.linen as nn
    import jax
    import jax.numpy as jnp
    import optax

    from flaxdiff_tpu.parallel import create_mesh
    from flaxdiff_tpu.predictors import EpsilonPredictionTransform
    from flaxdiff_tpu.schedulers import CosineNoiseSchedule
    from flaxdiff_tpu.trainer import DiffusionTrainer, TrainerConfig
    from flaxdiff_tpu.trainer.checkpoints import Checkpointer

    class TinyUnet(nn.Module):
        @nn.compact
        def __call__(self, x, t, cond):
            temb = nn.Dense(16)(t[:, None].astype(x.dtype))
            h = nn.Conv(16, (3, 3))(x) + temb[:, None, None, :]
            h = nn.swish(h)
            return nn.Conv(x.shape[-1], (3, 3))(h)

    model = TinyUnet()
    mesh = create_mesh(axes={"data": 2, "fsdp": 4})

    return DiffusionTrainer(
        apply_fn=lambda p, x, t, c: model.apply({"params": p}, x, t, c),
        init_fn=lambda key: model.init(
            key, jnp.zeros((1, 16, 16, 3)), jnp.zeros((1,)), None)["params"],
        tx=optax.adam(1e-3),
        schedule=CosineNoiseSchedule(timesteps=100),
        transform=EpsilonPredictionTransform(),
        mesh=mesh,
        config=TrainerConfig(normalize=True, keep_best_state=False,
                             checkpoint_on_sigterm=False),
        checkpointer=Checkpointer(ckpt_dir, max_to_keep=2),
    ), mesh


def data_iterator(global_batch: int):
    """Per-process grain pipeline over the synthetic dataset: the
    IndexSampler's ShardByJaxProcess hands each process a disjoint record
    shard; batches come out at the LOCAL batch size."""
    from flaxdiff_tpu.data.dataloaders import get_dataset_grain
    from flaxdiff_tpu.data.dataset_map import get_dataset

    data = get_dataset_grain(get_dataset("synthetic", n=64, image_size=16),
                             batch_size=global_batch, image_size=16,
                             worker_count=0)
    import jax
    assert data["local_batch_size"] == global_batch // jax.process_count()
    return data["train"](seed=7)


def main():
    phase = sys.argv[1]
    proc_id = int(sys.argv[2])
    port = sys.argv[3]
    ckpt_dir = sys.argv[4]

    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(coordinator_address=f"localhost:{port}",
                               num_processes=2, process_id=proc_id)
    assert jax.process_count() == 2
    assert jax.device_count() == 8 and jax.local_device_count() == 4

    trainer, mesh = build_trainer(ckpt_dir)
    losses = []

    if phase == "train":
        it = data_iterator(global_batch=8)
        for _ in range(3):
            batch = next(it)
            assert batch["sample"].shape[0] == 4   # local half of 8
            gb = trainer.put_batch(batch)
            # the assembled batch is GLOBAL: full batch over the mesh
            assert gb["sample"].shape[0] == 8
            losses.append(float(jax.device_get(trainer.train_step(gb))))
        assert trainer.save_checkpoint(force=True)
        trainer.checkpointer.wait_until_finished()
    elif phase == "restore":
        step = trainer.restore_checkpoint()
        assert step == 3, f"expected restored step 3, got {step}"
        it = data_iterator(global_batch=8)
        gb = trainer.put_batch(next(it))
        losses.append(float(jax.device_get(trainer.train_step(gb))))
        assert int(jax.device_get(trainer.state.step)) == 4
    else:
        raise SystemExit(f"unknown phase {phase}")

    print("RESULT " + json.dumps({"proc": proc_id, "phase": phase,
                                  "losses": losses}), flush=True)


if __name__ == "__main__":
    main()

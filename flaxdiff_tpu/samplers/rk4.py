"""Classic RK4 ODE sampler (reference flaxdiff/samplers/rk4_sampler.py:10-33).

Four NFEs per step on dx/dsigma = eps. Midpoint slopes need t(sigma), so a
SigmaSchedule (signal == 1) is required, as in the reference (which gates
on GeneralizedNoiseScheduler).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..schedulers.common import SigmaSchedule, bcast_right
from .common import Sampler


class RK4Sampler(Sampler):
    def step(self, denoise, x, t_cur, t_next, key, state, schedule, step_index):
        assert isinstance(schedule, SigmaSchedule), \
            "RK4Sampler requires a SigmaSchedule (sigma-parameterized)"
        b = x.shape[0]
        t_c = jnp.broadcast_to(t_cur, (b,))
        t_n = jnp.broadcast_to(t_next, (b,))
        sigma_c = schedule.sigmas(t_c)
        sigma_n = schedule.sigmas(t_n)
        h = bcast_right(sigma_n - sigma_c, x.ndim)
        sigma_mid = 0.5 * (sigma_c + sigma_n)
        t_mid = schedule.timesteps_from_sigmas(sigma_mid)

        def slope(xi, ti):
            _, eps = denoise(xi, ti)
            return eps

        k1 = slope(x, t_c)
        k2 = slope(x + 0.5 * h * k1, t_mid)
        k3 = slope(x + 0.5 * h * k2, t_mid)
        k4 = slope(x + h * k3, t_n)
        x_next = x + (h / 6.0) * (k1 + 2 * k2 + 2 * k3 + k4)
        return x_next, state

"""Chaos suite: tiny CPU train runs under deterministic fault plans.

Every scenario here replays exactly (seeded FaultPlan + seeded data),
exercising the SAME production code paths a pod failure hits: corrupt
checkpoints fall back, transient save I/O retries, SIGTERM mid-async-save
still flushes, a wedged loader trips the watchdog, and injected NaNs
roll back to the best state — all visible in the resilience-event log.
"""
import os
import signal
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from flaxdiff_tpu import resilience as R
from flaxdiff_tpu.predictors import EpsilonPredictionTransform
from flaxdiff_tpu.schedulers import CosineNoiseSchedule
from flaxdiff_tpu.trainer import Checkpointer, DiffusionTrainer, TrainerConfig

pytestmark = pytest.mark.chaos


def _make_trainer(mesh, tmp_path=None, event_log=None, **cfg_kw):
    import flax.linen as nn

    class Tiny(nn.Module):
        @nn.compact
        def __call__(self, x, t, cond=None):
            h = nn.Conv(8, (3, 3))(x)
            return nn.Conv(x.shape[-1], (3, 3))(jnp.tanh(h))

    model = Tiny()

    def apply_fn(params, x, t, cond):
        return model.apply({"params": params}, x, t, None)

    def init_fn(key):
        return model.init(key, jnp.zeros((1, 8, 8, 1)),
                          jnp.zeros((1,)))["params"]

    ckpt = None
    if tmp_path is not None:
        ckpt = Checkpointer(str(tmp_path), event_log=event_log)
    return DiffusionTrainer(
        apply_fn=apply_fn, init_fn=init_fn, tx=optax.adam(1e-3),
        schedule=CosineNoiseSchedule(timesteps=100),
        transform=EpsilonPredictionTransform(),
        mesh=mesh,
        config=TrainerConfig(normalize=False, log_every=2, **cfg_kw),
        checkpointer=ckpt)


def _data(rng, batch=8):
    while True:
        yield {"sample": rng.normal(size=(batch, 8, 8, 1))
               .astype(np.float32)}


def test_corrupt_latest_plus_transient_save_fault_recovers(
        mesh, tmp_path, rng):
    """The acceptance scenario: latest checkpoint corrupted AND a
    transient save I/O fault injected — fit restores from the previous
    good step, finishes with finite loss, and the event log records both
    the fallback restore and the retried save."""
    ckdir = tmp_path / "ckpt"
    trainer = _make_trainer(mesh, ckdir)
    trainer.fit(_data(rng), total_steps=4, save_every=2)   # saves 2, 4
    trainer.checkpointer.wait_until_finished()
    assert trainer.checkpointer.latest_step() == 4
    trainer.checkpointer.close()

    R.corrupt_step_dir(str(ckdir), 4)
    ev = R.EventLog("chaos")
    # one transient I/O failure on the next fresh save attempt
    plan = R.FaultPlan([R.FaultSpec("ckpt.save", at=(1,), times=1)], seed=0)
    with R.use_event_log(ev), plan.installed():
        trainer2 = _make_trainer(mesh, ckdir, event_log=ev)
        restored = trainer2.restore_checkpoint()
        assert restored == 2                    # fell back past corrupt 4
        assert ev.count("fallback_restore", "ckpt.restore") >= 1

        hist = trainer2.fit(_data(rng), total_steps=3, save_every=2)
        trainer2.checkpointer.wait_until_finished()

    assert np.isfinite(hist["final_loss"])
    assert len(hist["steps"]) > 0
    # step 4 is re-reached post-restore but already on disk: surfaced as
    # a skip, not counted as a fresh save
    assert ev.count("save_skipped", "ckpt.save") >= 1
    assert hist["saves"]["skipped_exists"] >= 1
    # the final save (step 5) hit the injected fault and was retried
    assert ev.count("retry", "ckpt.save") >= 1
    assert hist["saves"]["started"] >= 1
    assert trainer2.checkpointer.latest_step() == 5
    # the run's resilience summary surfaces the whole story
    assert hist["resilience"]["resilience/fallback_restore.ckpt.restore"] >= 1
    assert hist["resilience"]["resilience/retry.ckpt.save"] >= 1
    trainer2.checkpointer.close()


def test_sigterm_mid_async_save_still_flushes(mesh, tmp_path, rng):
    """host.sigterm fault right after a save_every save is dispatched:
    the preemption path must still flush the in-flight async save."""
    ev = R.EventLog("chaos")
    plan = R.FaultPlan(
        [R.FaultSpec("host.sigterm", at=(3,), error="flag", times=1)])
    with R.use_event_log(ev), plan.installed():
        trainer = _make_trainer(mesh, tmp_path / "ck", event_log=ev)
        hist = trainer.fit(_data(rng), total_steps=50, save_every=2)
    assert hist["preempted"] is True
    assert not hist["steps"] or hist["steps"][-1] < 50
    assert ev.count("fault_injected", "host.sigterm") == 1
    assert ev.count("preempt", "train.step") == 1
    trainer.checkpointer.wait_until_finished()
    saved = trainer.checkpointer.latest_step()
    assert saved is not None and saved >= 2
    # handler restored: later SIGTERMs are not swallowed
    assert signal.getsignal(signal.SIGTERM) not in (None,)
    trainer.checkpointer.close()


def test_step_nan_fault_triggers_rollback_event(mesh, rng):
    ev = R.EventLog("chaos")
    plan = R.FaultPlan(
        [R.FaultSpec("step.nan", at=(3,), error="flag", times=1)])
    with R.use_event_log(ev), plan.installed():
        trainer = _make_trainer(mesh)
        hist = trainer.fit(_data(rng), total_steps=8)
    assert ev.count("fault_injected", "step.nan") == 1
    assert ev.count("rollback", "train.step") == 1
    # training continued past the poisoned readback to a finite loss
    assert np.isfinite(hist["final_loss"])
    assert hist["resilience"]["resilience/rollback.train.step"] == 1


def test_wedged_loader_trips_watchdog(mesh, tmp_path, rng):
    """A data iterator that wedges mid-run: the watchdog fires, records
    the stall, and fit returns cleanly through the preemption path
    instead of hanging."""
    def stalling_data():
        src = _data(rng)
        for i, batch in enumerate(src):
            if i == 2:
                time.sleep(3.0)         # wedge >> watchdog timeout
            yield batch

    ev = R.EventLog("chaos")
    with R.use_event_log(ev):
        trainer = _make_trainer(mesh, tmp_path / "ck", event_log=ev,
                                watchdog_timeout=0.8)
        t0 = time.monotonic()
        hist = trainer.fit(stalling_data(), total_steps=200, save_every=50)
        elapsed = time.monotonic() - t0
    assert hist["watchdog_fired"] is True
    assert hist["preempted"] is True
    assert ev.count("watchdog_stall", "train.step") >= 1
    assert elapsed < 60                     # returned, did not hang
    trainer.checkpointer.wait_until_finished()
    assert trainer.checkpointer.latest_step() is not None
    trainer.checkpointer.close()


def test_watchdog_quiet_on_healthy_run(mesh, rng):
    ev = R.EventLog("chaos")
    with R.use_event_log(ev):
        trainer = _make_trainer(mesh, watchdog_timeout=30.0)
        hist = trainer.fit(_data(rng), total_steps=4)
    assert hist["watchdog_fired"] is False
    assert hist["preempted"] is False
    assert ev.count("watchdog_stall") == 0
    assert np.isfinite(hist["final_loss"])


def test_step_nan_rollback_under_pipelined_loop(mesh, rng):
    """ISSUE 5 satellite: the step.nan chaos scenario replayed under
    the sync-free loop (pipeline_depth=2, sampled telemetry off-hub) —
    the poisoned readback still lands in the loss window, still takes
    the detector path, and still rolls back exactly once."""
    ev = R.EventLog("chaos")
    plan = R.FaultPlan(
        [R.FaultSpec("step.nan", at=(3,), error="flag", times=1)])
    with R.use_event_log(ev), plan.installed():
        trainer = _make_trainer(mesh, pipeline_depth=2,
                                telemetry_sample_every=4)
        hist = trainer.fit(_data(rng), total_steps=8)
    assert ev.count("fault_injected", "step.nan") == 1
    assert ev.count("rollback", "train.step") == 1
    assert np.isfinite(hist["final_loss"])


def test_sigterm_checkpoints_last_settled_step_under_pipelining(
        mesh, tmp_path, rng):
    """Preemption under bounded in-flight dispatch: with up to 2 steps
    in flight at SIGTERM time, the exit save must persist the last
    SETTLED state — the checkpoint step equals the state's own step
    counter (every dispatched step settles before orbax serializes),
    never a torn in-between."""
    ev = R.EventLog("chaos")
    plan = R.FaultPlan(
        [R.FaultSpec("host.sigterm", at=(4,), error="flag", times=1)])
    with R.use_event_log(ev), plan.installed():
        trainer = _make_trainer(mesh, tmp_path / "ck", event_log=ev,
                                pipeline_depth=2)
        hist = trainer.fit(_data(rng), total_steps=50, save_every=10)
    assert hist["preempted"] is True
    trainer.checkpointer.wait_until_finished()
    saved = trainer.checkpointer.latest_step()
    state_step = int(jax.device_get(trainer.state.step))
    assert saved == state_step >= 4
    # the saved state is fully settled and finite
    restored = _make_trainer(mesh, tmp_path / "ck")
    assert restored.restore_checkpoint() == saved
    for leaf in jax.tree_util.tree_leaves(
            jax.device_get(restored.state.params)):
        assert np.all(np.isfinite(leaf))
    trainer.checkpointer.close()
    restored.checkpointer.close()


def test_chaos_run_from_env_plan(mesh, monkeypatch, rng):
    """The env-driven arming path: FLAXDIFF_FAULT_PLAN JSON installs a
    plan without code changes (how a real chaos job arms itself)."""
    plan = R.FaultPlan(
        [R.FaultSpec("step.nan", at=(2,), error="flag", times=1)])
    monkeypatch.setenv(R.faults.ENV_VAR, plan.to_json())
    # force a fresh env read, then restore whatever was active
    prev = R.install_plan(None)
    R.faults._env_loaded = False
    ev = R.EventLog("chaos")
    try:
        with R.use_event_log(ev):
            trainer = _make_trainer(mesh)
            trainer.fit(_data(rng), total_steps=4)
        assert ev.count("fault_injected", "step.nan") == 1
    finally:
        R.install_plan(prev)

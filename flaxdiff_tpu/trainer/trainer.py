"""DiffusionTrainer: wires mesh, shardings, the jitted step, and the fit loop.

Parity with reference SimpleTrainer/DiffusionTrainer fit/train_loop
(trainer/simple_trainer.py:148-677, diffusion_trainer.py:41-370):
init/load state, epoch loop, NaN/abnormal-loss recovery with best-state
rollback, periodic logging, checkpoint save on improvement. TPU-native
differences: params + optimizer + EMA sharded over the `fsdp` axis from
initialization on (the reference replicates everything), the step is one
jit program with donated state, and the loss readback that the reference
pays every step (simple_trainer.py:542) happens only at log cadence.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, Iterator, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel import fsdp_sharding_tree, sharding_tree
from ..parallel.mesh import batch_spec
from ..profiling import MFUMeter, compiled_flops, device_peak_flops, mfu
from ..predictors import PredictionTransform
from ..resilience import events as _res_events
from ..resilience import faults as _res_faults
from ..telemetry import global_telemetry as _global_telemetry
from ..schedulers.common import NoiseSchedule
from ..typing import Policy, PyTree
from .train_state import TrainState
from .train_step import TrainStepConfig, make_train_step


# The fit loop's ONLY host-synchronization primitives, routed through
# module-level names so tests can count them: the sync-free-loop
# contract ("off-sample steps perform no block_until_ready and no
# scalar loss fetch") is asserted by monkeypatching these with counting
# wrappers — a future refactor that sneaks a per-step sync back in
# fails that test instead of silently re-serializing the pipeline.

def _block_until_ready(x) -> None:
    jax.block_until_ready(x)


def _is_ready(x) -> bool:
    """Non-blocking completion query (False = still in flight)."""
    try:
        return bool(x.is_ready())
    except AttributeError:      # non-jax leaf / very old jax
        return True


def _fetch_losses(arrs):
    """The one host sync of a log window: read the device-resident loss
    window back as floats (blocks until the newest step settles)."""
    return [float(v) for v in jax.device_get(list(arrs))]


def _fetch_ring(ring):
    """The loss-ring variant of the window sync: ONE device_get of the
    in-graph ring array covers every step in the window (blocks until
    the newest step settles). Module-level for the same counting-mock
    contract as _fetch_losses."""
    return np.asarray(jax.device_get(ring))


def _fetch_gate_events(arr):
    """Read the [3] in-graph gate-activation counter
    (TrainState.gate_events) back to host as int64 — piggybacks on the
    log window, where the window fetch has already settled the
    pipeline. Module-level for the same counting-mock contract as
    _fetch_losses."""
    return np.asarray(jax.device_get(arr)).astype(np.int64)


# a "compile" first step no slower than this multiple of the median
# steady step did not actually compile (warm persistent cache) and is
# re-attributed productive — see GoodputLedger.reattribute
_COMPILE_RECLASS_RATIO = 2.0


@dataclasses.dataclass
class TrainerConfig:
    ema_decay: float = 0.999
    uncond_prob: float = 0.12
    weighted_loss: bool = True
    normalize: bool = True
    log_every: int = 100
    # loss <= this, NaN or Inf triggers best-state rollback
    # (reference simple_trainer.py:542-575)
    abnormal_loss_floor: float = 1e-8
    keep_best_state: bool = True
    seed: int = 0
    # Preemption safety: on SIGTERM (the TPU-pod eviction signal) the fit
    # loop checkpoints and returns cleanly instead of dying mid-step.
    # The reference has no preemption handling (a host loss kills the
    # job, SURVEY §5.3).
    checkpoint_on_sigterm: bool = True
    # Flat-parameter training (trainer/optim.py rationale): params, EMA
    # and optimizer state live as ONE padded vector per dtype; the model
    # unflattens inside the loss, AD returns flat grads, and every
    # optimizer/EMA/apply update is a handful of fused HBM-floor kernels
    # instead of ~2 launch-bound kernels per leaf. Requires an
    # ELEMENTWISE optax chain (adam/adamw/sgd/lion [+ global-norm
    # clip]; NOT lamb/adafactor/per-block transforms). Checkpoint
    # layout changes (flat vectors) — choose per run.
    flat_params: bool = False
    # In-training profiler capture: when set, a jax.profiler trace of
    # `profile_steps` steps starting at `profile_at_step` (post-warmup)
    # lands in profile_dir.
    profile_dir: Optional[str] = None
    profile_at_step: int = 10
    profile_steps: int = 5
    # Automated device-profile windows (telemetry/devprof.py): > 0
    # opens a jax.profiler window of `profile_steps` steps every
    # `profile_cadence` steps under an ENABLED telemetry hub, parses
    # the capture into a `devprof.jsonl` attribution row (op families,
    # modules, collective split) reconciled against the program
    # registry (measured MFU, roofline verdict, comm calibration).
    # Window overhead lands in the `profile` phase + goodput bucket;
    # off-window steps pay two int compares — no device work, no host
    # syncs. Independent of the one-shot profile_dir capture above.
    profile_cadence: int = 0
    # On-demand arming: when this path exists at a log step, it is
    # consumed and ONE profile window opens at the next step — the
    # "profile the live run NOW" knob (also reachable while
    # profile_cadence is 0).
    profile_trigger: Optional[str] = None
    # Heartbeat watchdog (resilience/watchdog.py): None disables. When a
    # step (or the loader feeding it) stalls past this many seconds, a
    # `watchdog_stall` event is recorded and the stall action runs:
    # "sigterm" re-uses the preemption path (clean checkpoint-and-exit),
    # "flag" only marks stop so the loop exits at its next iteration.
    # The first step is exempt (jit compile can legitimately exceed it).
    watchdog_timeout: Optional[float] = None
    watchdog_action: str = "sigterm"
    # Resume INSIDE fit, before the first step: with a coordinated
    # checkpointer this is the consensus-restore round (every host
    # restores the same committed step or fit raises before stepping);
    # without one it is the ordinary fallback restore. A missing
    # checkpoint is a cold start, not an error.
    restore_at_start: bool = False
    # Training-health monitor (telemetry/numerics.py): every N steps the
    # trainer dispatches a SECOND compiled step that also computes
    # per-module grad/param norms, update ratios and non-finite counts
    # in-graph; 0 disables and off-cadence steps run the unmonitored
    # program unchanged (zero extra device work). Cadence steps pay one
    # aux readback (a host sync) plus the host-side detector.
    numerics_cadence: int = 0
    # What a detected anomaly does: "warn" records events/metrics only;
    # "skip_step" compiles the monitored step with an in-graph
    # non-finite gate (a poisoned step's update never lands — z-score
    # spikes still only warn, the state is donated by the time the host
    # sees them); "rollback" restores the best state (or walks back to
    # the newest restorable checkpoint when no best state exists yet —
    # the PR-1/2 fallback-restore path) on any hard anomaly.
    anomaly_action: str = "warn"
    anomaly_zscore: float = 6.0
    anomaly_window: int = 50
    # Bounded-depth asynchronous dispatch: the fit loop keeps up to
    # this many steps in flight (dispatch is async; the host runs
    # ahead). Exceeding the bound waits — non-blockingly checked first
    # — on the OLDEST in-flight step, so the device stays at most
    # `pipeline_depth` steps behind the host instead of the host
    # enqueueing unbounded work (and pinning unbounded batch buffers).
    # 1 ~= classic one-deep double buffering; 0/negative disables the
    # bound (the log-cadence loss fetch is then the only settle point).
    pipeline_depth: int = 2
    # Sampled device-phase timing (telemetry/phases.py): with an
    # enabled telemetry hub, close async dispatch with
    # block_until_ready only every N-th step — off-sample steps add
    # ZERO host syncs, and phase/goodput attribution degrades to
    # window granularity (docs/OBSERVABILITY.md "Sampled phase
    # timing"). 1 = exact per-step device timing (the pre-pipelining
    # behavior). Ignored when telemetry is disabled.
    telemetry_sample_every: int = 1
    # In-graph loss ring (train_step.py / train_state.py): > 0 carries
    # a device-resident [W] ring in the TrainState that the jitted step
    # writes at slot step % W. The fit loop then fetches losses ONCE
    # per W steps — one readback per window even at log_every=1 — and
    # emits the whole window's per-step losses retroactively
    # (`window_losses` in the log metrics; recovery checks see every
    # value, delayed by at most W steps). 0 (default) keeps the
    # pre-ring behavior AND the pre-ring TrainState pytree — ring
    # checkpoints carry one extra [W] leaf, so flip it per run, not
    # mid-run.
    loss_ring: int = 0
    # In-graph non-finite gate on EVERY step (train_step.py
    # _finite_only_gate): any non-finite element of the updated
    # params/opt-state/EMA keeps its previous value (elementwise — a
    # global verdict would ~4x compile time, see the gate's docstring),
    # so the live state — and any checkpoint taken from it — is finite
    # by construction. This is what lets the save path skip the
    # per-save loss fetch; disabling it restores the exact ungated
    # step program AND the legacy synchronous save-cadence loss check.
    gate_nonfinite: bool = True
    # Gate-activation visibility (PR 5 follow-up): carry a [3] int32
    # counter in the TrainState that the in-graph gate increments with
    # the number of params/opt-state/EMA elements it masked; the fit
    # loop reads it once per log window (no extra pipeline sync — the
    # window fetch already settled everything) and surfaces deltas as
    # `numerics/gate_activations*` counters + a `gate_activated`
    # event. OPT-IN: the count is a reduction over every state leaf,
    # which measurably blows up XLA CPU compile of the step (the exact
    # pathology `_finite_only_gate`'s elementwise design avoids), and
    # the extra leaf changes the checkpoint pytree — flip per run, not
    # mid-run. Requires gate_nonfinite.
    gate_counter: bool = False


class DiffusionTrainer:
    """Owns sharded state + the compiled step; drives the training loop."""

    def __init__(self,
                 apply_fn: Callable,
                 init_fn: Callable[[jax.Array], PyTree],
                 tx: optax.GradientTransformation,
                 schedule: NoiseSchedule,
                 transform: PredictionTransform,
                 mesh: Optional[Mesh] = None,
                 config: TrainerConfig = TrainerConfig(),
                 policy: Optional[Policy] = None,
                 autoencoder: Optional[Any] = None,
                 null_cond: Optional[PyTree] = None,
                 checkpointer: Optional[Any] = None,
                 telemetry: Optional[Any] = None,
                 elastic: Optional[Any] = None,
                 plan: Optional[Any] = None,
                 partition_rules: Optional[Sequence] = None):
        """apply_fn(params, x_t, t, cond) -> raw output;
        init_fn(key) -> params (closes over example input shapes).

        `plan`: "auto" resolves mesh AND partition rules from the
        auto-parallelism planner (`parallel/planner.resolve_plan` —
        static search over the param tree, cached in
        $FLAXDIFF_PLAN_CACHE, committed to the telemetry hub's program
        registry), replacing the hand-written mesh/rule table; a
        `PlanDecision` applies a previously-searched plan verbatim.
        With a plan, `mesh` may be None. `partition_rules` pins an
        explicit `match_partition_rules` table (the planner's probe
        harness and tests use it; a resolved plan overrides it).

        `telemetry`: a telemetry.Telemetry hub; None falls back to the
        process-global hub at fit time (disabled by default, so
        un-instrumented runs keep fully-async step dispatch).

        `elastic`: a resilience.ElasticWorldManager. The fit loop then
        survives a lost peer by shrinking the world (instead of
        checkpoint-and-exit on coordination_lost), admits parked
        replacement hosts at commit boundaries, and turns hard
        numerics anomalies into pod quorum votes
        (docs/RESILIENCE.md "Elastic world")."""
        self.mesh = mesh
        self.config = config
        self.telemetry = telemetry
        self.elastic = elastic
        self.schedule = schedule
        self.transform = transform
        self.checkpointer = checkpointer
        self._apply_fn = apply_fn

        self._param_template = None
        if config.flat_params:
            from .optim import param_template, unflatten_params
            key_t = jax.random.PRNGKey(config.seed)
            self._param_template = param_template(
                jax.eval_shape(lambda k: init_fn(k),
                               jax.random.split(key_t)[0]))
            template = self._param_template
            inner_apply, inner_init = apply_fn, init_fn

            def apply_fn(flats, x, t, cond):        # noqa: F811
                # the unflatten runs INSIDE the differentiated function:
                # its AD transpose re-assembles leaf gradients into the
                # flat vector, so grads arrive flat for free
                return inner_apply(unflatten_params(template, flats),
                                   x, t, cond)

            def init_fn(key):                       # noqa: F811
                from .optim import flatten_params
                return flatten_params(inner_init(key), 1024)

        from ..telemetry.numerics import ANOMALY_ACTIONS
        if config.anomaly_action not in ANOMALY_ACTIONS:
            raise ValueError(f"anomaly_action {config.anomaly_action!r} "
                             f"not in {ANOMALY_ACTIONS}")
        if config.gate_counter and not config.gate_nonfinite:
            raise ValueError("gate_counter counts the in-graph gate's "
                             "activations — it requires gate_nonfinite")

        step_cfg = TrainStepConfig(
            uncond_prob=config.uncond_prob,
            ema_decay=config.ema_decay,
            normalize=config.normalize,
            weighted_loss=config.weighted_loss,
        )
        # kept for the lazily-jitted NaN-provenance probe (the rebound
        # flat-params apply_fn, not the caller's original)
        self._probe_inputs = (apply_fn, schedule, transform,
                              dict(config=step_cfg, policy=policy,
                                   autoencoder=autoencoder,
                                   null_cond=null_cond))
        step_fn = make_train_step(apply_fn, schedule, transform, step_cfg,
                                  policy=policy, autoencoder=autoencoder,
                                  null_cond=null_cond,
                                  gate_nonfinite=config.gate_nonfinite)
        monitored_step_fn = None
        if config.numerics_cadence > 0:
            from ..telemetry.numerics import NumericsConfig
            monitored_step_fn = make_train_step(
                apply_fn, schedule, transform, step_cfg,
                policy=policy, autoencoder=autoencoder,
                null_cond=null_cond,
                # the monitored twin must gate whenever the plain step
                # does — an ungated cadence step would be the one hole
                # in the "state is finite by construction" save guard
                gate_nonfinite=config.gate_nonfinite,
                numerics=NumericsConfig(
                    # a flat-param state has no module structure
                    per_module=not config.flat_params,
                    # both recovery actions gate in-graph: under
                    # `rollback` the restore replaces the step anyway,
                    # and an unapplied poisoned update keeps the
                    # provenance pass exact (an applied one smears NaNs
                    # into EVERY module's params before the host can
                    # react). Only `warn` leaves updates untouched —
                    # its contract is strictly observational.
                    skip_nonfinite=(config.anomaly_action
                                    in ("skip_step", "rollback"))))

        # fp16 compute needs loss scaling (reference diffusion_trainer.py
        # :214-240 DynamicScale path); bf16's exponent range does not.
        dynamic_scale = None
        if policy is not None and policy.compute_dtype == jnp.float16:
            from flax.training.dynamic_scale import DynamicScale
            dynamic_scale = DynamicScale()

        def create_state(key):
            init_key, train_key = jax.random.split(key)
            params = init_fn(init_key)
            return TrainState.create(
                apply_fn=apply_fn, params=params, tx=tx, rng=train_key,
                ema_decay=config.ema_decay, dynamic_scale=dynamic_scale,
                loss_ring_size=max(config.loss_ring, 0),
                gate_counter=config.gate_counter)

        key = jax.random.PRNGKey(config.seed)
        state_shapes = jax.eval_shape(create_state, key)

        self.plan_decision = None
        self._partition_rules = partition_rules
        if plan is not None:
            from ..parallel.planner import resolve_plan
            decision = resolve_plan(plan, state_shapes.params,
                                    telemetry=telemetry)
            mesh = decision.build_mesh()
            self._partition_rules = decision.rules
            self.plan_decision = decision
        if mesh is None:
            raise ValueError("DiffusionTrainer needs a mesh or a plan")
        self.mesh = mesh

        self.state_specs = fsdp_sharding_tree(
            state_shapes, mesh, rules=self._partition_rules)
        self.state_shardings = sharding_tree(self.state_specs, mesh)

        with mesh:
            self.state = jax.jit(
                create_state, out_shardings=self.state_shardings)(key)

        self._batch_axis = batch_spec(mesh)

        # kept so an elastic mesh rebuild can re-jit the same programs
        # against the new mesh/shardings (_compile_programs)
        self._step_fn = step_fn
        self._monitored_fn = monitored_step_fn
        self._compile_programs()

        self.best_loss = float("inf")
        self.best_state: Optional[TrainState] = None
        # the step the best state was snapshotted at — the data plane's
        # rewind target when a rollback restores it
        self.best_step: Optional[int] = None

        if self._param_template is not None and checkpointer is not None:
            # flat-state checkpoints are unreadable without the template
            # (inference/pipeline.py from_checkpoint): persist it beside
            # the shards from whoever owns the flat state — every
            # producer, not just the CLI
            self._write_param_template()

    def _write_param_template(self):
        import json as _json

        from .optim import TEMPLATE_FILENAME, serialize_template
        if jax.process_index() != 0:
            return
        # epath, not builtin open: the checkpointer itself writes through
        # it, so object-store directories (gs://...) that hold a valid
        # flat checkpoint get a readable template beside it instead of a
        # local-only warn + guaranteed inference FileNotFoundError
        from etils import epath
        path = epath.Path(self.checkpointer.directory) / TEMPLATE_FILENAME
        try:
            path.write_text(
                _json.dumps(serialize_template(self._param_template)))
        except OSError as e:
            import warnings
            warnings.warn(f"could not write {path}: {e}; flat-params "
                          "checkpoints need it for inference restore",
                          stacklevel=2)

    def _compile_programs(self):
        """(Re)bind the jitted step programs to the CURRENT mesh and
        state shardings — at construction, and again after an elastic
        mesh rebuild (the old programs bake in the old device
        assignment)."""
        mesh = self.mesh
        self._step = jax.jit(
            self._step_fn,
            donate_argnums=(0,),
            out_shardings=(self.state_shardings, NamedSharding(mesh, P())),
        )
        # the monitored twin: same program + in-graph numerics aux
        # (replicated scalars). Compiled separately so off-cadence steps
        # keep running the EXACT unmonitored program.
        self._step_monitored = None
        if self._monitored_fn is not None:
            self._step_monitored = jax.jit(
                self._monitored_fn,
                donate_argnums=(0,),
                out_shardings=(self.state_shardings,
                               NamedSharding(mesh, P()),
                               NamedSharding(mesh, P())),
            )
        self._probe = None      # lazily-jitted NaN-provenance pass
        self._step_flops: Dict[Any, Optional[float]] = {}

    # -- elastic world transitions -------------------------------------------
    def _rebuild_world_mesh(self, force: bool = False) -> bool:
        """Rebuild a 1-D `'data'` mesh over THIS host's local devices
        and re-shard/re-jit around it (elastic shrink helper).

        After a peer is lost, a mesh that spanned its devices is dead —
        every collective over it would hang — so the survivors' world
        re-forms over the devices they still own. A mesh that was
        already local-only (the per-host data-parallel layout the
        elastic chaos suite runs) survives unchanged, keeping its
        compiled programs and in-flight state (returns False).
        `force=True` rebuilds even a live local mesh."""
        local_count = sum(1 for d in self.mesh.devices.flat
                          if d.process_index == jax.process_index())
        all_local = local_count == self.mesh.devices.size
        if all_local and not force:
            return False
        from ..parallel.mesh import local_data_mesh
        new_mesh = local_data_mesh()
        shapes = jax.tree_util.tree_map(
            lambda x: (jax.ShapeDtypeStruct(x.shape, x.dtype)
                       if isinstance(x, jax.Array) else x), self.state)
        self.mesh = new_mesh
        # a searched plan is dead with the mesh it was searched for —
        # the shrunken world re-infers (and can re-plan at next launch)
        self._partition_rules = None
        self.plan_decision = None
        self.state_specs = fsdp_sharding_tree(shapes, new_mesh)
        self.state_shardings = sharding_tree(self.state_specs, new_mesh)
        self._batch_axis = batch_spec(new_mesh)
        if all_local:
            # live state is fully addressable: move it onto the new
            # mesh. (Post-shrink the old arrays reference dead devices
            # and are NOT moved — the consensus-step restore that
            # follows places fresh shards directly on the new mesh.)
            self.state = jax.device_put(self.state, self.state_shardings)
        self.best_state = None      # old-mesh arrays; re-seeded on restore
        self.best_step = None
        self._compile_programs()
        _res_events.global_event_log().record(
            "mesh_rebuilt", "elastic.world",
            detail=f"1-D 'data' mesh over {new_mesh.devices.size} local "
                   f"device(s); step programs re-jitted")
        return True

    def _elastic_restore(self, step: int) -> int:
        """Restore exactly `step` with shards placed onto the CURRENT
        mesh, independent of the live state's (possibly dead) old
        shardings — the post-transition variant of
        `restore_checkpoint`."""
        def absify(x, s):
            if isinstance(x, jax.Array) or hasattr(x, "shape"):
                return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s)
            return x
        abstract = jax.tree_util.tree_map(absify, self.state,
                                          self.state_shardings)
        self.state, meta = self.checkpointer.restore(abstract, step=step)
        best = float(meta.get("best_loss", float("inf")))
        self.best_loss = best if best > 0 else float("inf")
        if self.config.keep_best_state:
            self.best_state = jax.tree_util.tree_map(jnp.copy, self.state)
            self.best_step = int(step)
        return int(step)

    # -- flash autotuning ----------------------------------------------------
    def autotune_flash(self, global_batch: PyTree):
        """Per-shape flash-attention autotuning (ops/autotune.py): a
        `jax.eval_shape` scouting pass over the train step records every
        attention shape the model dispatches (no device work, nothing
        compiled), then measured probes pick block sizes / native-d per
        shape and persist them to the active autotuner's cache dir.
        Returns {shape_key: FlashPlan} for the shapes probed — empty
        when no autotuner is active (`ops.autotune.activate` /
        FLAXDIFF_FLASH_TUNE_CACHE) or every shape was already cached
        (the warm-cache contract: zero probes). Call BEFORE the first
        train step so the real compile picks the tuned plans up."""
        from ..ops import autotune as _autotune
        aut = _autotune.active()
        if aut is None:
            return {}
        from ..parallel.context import use_mesh
        batch = self._numeric_subtree(global_batch)
        with use_mesh(self.mesh):
            jax.eval_shape(self._step, self.state, batch)
        return aut.probe_pending()

    # -- profiling -----------------------------------------------------------
    def step_flops(self, global_batch: PyTree) -> Optional[float]:
        """Per-device FLOPs of the compiled train step (XLA cost analysis);
        cached per batch shape. None on backends without a cost model."""
        batch = self._numeric_subtree(global_batch)
        key = tuple((jax.tree_util.keystr(p), x.shape)
                    for p, x in jax.tree_util.tree_flatten_with_path(batch)[0])
        if key not in self._step_flops:
            from ..parallel.context import use_mesh
            with use_mesh(self.mesh):
                self._step_flops[key] = compiled_flops(
                    self._step, self.state, batch)
        return self._step_flops[key]

    def step_model_flops(self, global_batch: PyTree) -> Optional[float]:
        """Analytic per-STEP matmul+conv FLOPs at true shapes (jaxpr walk,
        no compile, no device work) — the unpadded "model FLOPs" MFU
        numerator. This is the whole-mesh count (the jaxpr is traced
        pre-partitioning); divide by device count for a per-chip figure.
        Meaningful only when the model's attention backend is visible to
        tracing ("xla"): pallas_call bodies are opaque, so a flash-backend
        trainer undercounts — build an xla-backend twin for counting."""
        from ..parallel.context import use_mesh
        from ..profiling import traced_model_flops
        batch = self._numeric_subtree(global_batch)
        with use_mesh(self.mesh):
            return traced_model_flops(self._step, self.state, batch)

    def _register_program_evidence(self, tel, global_batch,
                                   registered: set,
                                   compile_s, monitored_compiled: bool,
                                   flops_cost) -> Optional[str]:
        """Program evidence registry hook (telemetry/programs.py): one
        `programs.jsonl` row per compiled step program — the plain step
        at the first log window, the monitored twin once it has
        compiled. The jaxpr-FLOPs walk is tens of ms of host work and
        runs once per program; `flops_cost` is the XLA cost-analysis
        figure fit already computed when the backend has a peak (never
        triggered here — an AOT recompile of the train step on XLA CPU
        is the documented compile blowup)."""
        reg = getattr(tel, "programs", None)
        if reg is None:
            return None
        from ..parallel.context import use_mesh
        from ..profiling import jaxpr_flops
        batch = self._numeric_subtree(global_batch)
        sig = ",".join(
            f"{jax.tree_util.keystr(p)}{tuple(x.shape)}"
            for p, x in jax.tree_util.tree_flatten_with_path(batch)[0])
        targets = [("train_step", self._step, compile_s)]
        if monitored_compiled and self._step_monitored is not None:
            targets.append(("train_step_monitored",
                            self._step_monitored, None))
        for kind, prog, comp_s in targets:
            if kind in registered:
                continue
            registered.add(kind)
            flops_jaxpr = None
            collectives = comm_by_axis = None
            try:
                with use_mesh(self.mesh):
                    closed = jax.make_jaxpr(prog)(self.state, batch)
                flops_jaxpr = jaxpr_flops(closed.jaxpr)
                from ..analysis.shard_rules import collective_summary
                comm = collective_summary(
                    closed, dict(zip(self.mesh.axis_names,
                                     self.mesh.devices.shape))
                    if self.mesh is not None else None)
                collectives = int(comm["collectives"])
                comm_by_axis = dict(comm["comm_bytes_by_axis"])
            except Exception as e:  # noqa: BLE001 — evidence is
                # best-effort; a failed probe degrades the field only
                import logging
                logging.getLogger("flaxdiff_tpu.trainer").debug(
                    "train-step jaxpr probe failed: %s", e)
            from ..telemetry.memory import MemoryMonitor
            hbm = MemoryMonitor().sample().get("memory/peak_bytes_in_use")
            reg.record(
                kind, key=f"{kind}:{sig}",
                compile_ms=(comp_s * 1e3 if comp_s else None),
                flops_jaxpr=flops_jaxpr,
                flops_cost=(flops_cost if kind == "train_step"
                            else None),
                hbm_peak_bytes=hbm,
                collectives=collectives,
                comm_bytes_by_axis=comm_by_axis,
                extra={"compile_source": "first_step_busy"})
        # the plain step's registry identity — the devprof window-close
        # path reconciles its measured row against exactly this key
        return f"train_step:{sig}"

    # -- checkpointing -------------------------------------------------------
    def save_checkpoint(self, force: bool = False) -> bool:
        """Sharded async save of the live state (+best_loss meta)."""
        if self.checkpointer is None:
            return False
        step = int(jax.device_get(self.state.step))
        return self.checkpointer.save(
            step, self.state, meta={"best_loss": float(self.best_loss)},
            force=force)

    def restore_checkpoint(self, step: Optional[int] = None,
                           fallback: bool = True) -> int:
        """Restore state (sharded, shards placed directly on the mesh);
        returns the restored step (reference simple_trainer.py:339-367).

        With `fallback` (default) a corrupt/incomplete latest checkpoint
        walks back to the newest readable step instead of killing the
        run (`fallback_restore` events record each skip); an explicit
        `step` is always restored exactly or raises."""
        if self.checkpointer is None:
            raise ValueError("trainer has no checkpointer")
        from .checkpoints import abstract_state_like
        abstract = abstract_state_like(self.state)
        self.state, meta = self.checkpointer.restore(abstract, step=step,
                                                     fallback=fallback)
        best = float(meta.get("best_loss", float("inf")))
        # best_loss == 0 is the reference's corrupt-checkpoint sentinel
        # (simple_trainer.py:352) — reset rather than trust it.
        self.best_loss = best if best > 0 else float("inf")
        # Seed best_state from the restored state so NaN rollback stays
        # armed after resume (the restored best_loss may never be beaten).
        restored = int(jax.device_get(self.state.step))
        if self.config.keep_best_state:
            self.best_state = jax.tree_util.tree_map(jnp.copy, self.state)
            self.best_step = restored
        return restored

    # -- data movement -------------------------------------------------------
    def put_batch(self, batch: PyTree) -> PyTree:
        """Host-local numpy batch -> global sharded jax arrays.

        Non-numeric entries (e.g. raw caption strings kept for validation
        logging) are dropped here: the jitted step's contract only covers
        "sample" and the numeric "cond" tree (train_step.py:57)."""
        def put(x):
            x = np.asarray(x)
            spec_axes = (self._batch_axis[0] if len(self._batch_axis) else None)
            spec = P(*((spec_axes,) + (None,) * (x.ndim - 1)))
            return jax.make_array_from_process_local_data(
                NamedSharding(self.mesh, spec), x)
        return jax.tree_util.tree_map(put, self._numeric_subtree(batch))

    # -- core loop -----------------------------------------------------------
    @staticmethod
    def _numeric_subtree(batch: PyTree) -> PyTree:
        """Keep only the leaves the jitted step consumes — numpy string
        arrays (raw captions) cannot be traced."""
        def keep(x):
            if isinstance(x, (str, bytes)):
                return False
            if isinstance(x, (list, tuple)):
                return not any(isinstance(e, (str, bytes)) for e in x)
            return not (isinstance(x, np.ndarray)
                        and x.dtype.kind in ("U", "S", "O"))
        if isinstance(batch, dict):
            out = {}
            for k, v in batch.items():
                if isinstance(v, dict):
                    sub = DiffusionTrainer._numeric_subtree(v)
                    if sub:
                        out[k] = sub
                elif keep(v):
                    out[k] = v
            return out
        return batch

    def train_step(self, batch: PyTree):
        # Scoped mesh declaration: mesh-aware modules (attention backend
        # "ring") read it during the lazy first-call trace. Scoping per
        # call (rather than a global set in __init__) keeps two trainers
        # with different meshes in one process from cross-capturing, and
        # works when steps are driven from a worker thread.
        from ..parallel.context import use_mesh
        with use_mesh(self.mesh):
            self.state, loss = self._step(self.state,
                                          self._numeric_subtree(batch))
        return loss

    def train_step_monitored(self, batch: PyTree):
        """The numerics-cadence step: returns (loss, aux) where `aux` is
        the in-graph health pytree (telemetry/numerics.py). Requires
        `numerics_cadence > 0` at construction."""
        from ..parallel.context import use_mesh
        with use_mesh(self.mesh):
            self.state, loss, aux = self._step_monitored(
                self.state, self._numeric_subtree(batch))
        return loss, aux

    # -- training-health internals -------------------------------------------
    def _poison_module_params(self) -> str:
        """`numerics.nan` chaos site: corrupt the params of ONE
        deterministic module (first in sorted key order, at the same
        module level the numerics breakdown reports) with NaNs — the
        planted non-finite gradient the provenance pass must localize.
        Flat-param states have no modules; the whole vector is poisoned
        (provenance then degrades to the global count)."""
        from ..telemetry.numerics import unwrap_module_tree
        params = self.state.params

        def nan_like(tree):
            return jax.tree_util.tree_map(
                lambda x: x * jnp.float32(jnp.nan).astype(x.dtype), tree)

        inner, path = unwrap_module_tree(params)
        if isinstance(inner, dict) and inner:
            name = sorted(inner)[0]
            poisoned = dict(inner)
            poisoned[name] = nan_like(inner[name])
            for key in reversed(path):      # re-wrap the envelope
                poisoned = {key: poisoned}
        else:
            name, poisoned = "<flat>", nan_like(params)
        self.state = self.state.replace(params=poisoned)
        return name

    def _nan_provenance(self, batch: PyTree, tel, step: int):
        """On first non-finite detection: re-run ONE gradient pass (no
        update, no donation — the live state survives) and name the
        top-level module(s) whose grads or params hold non-finite
        values. The probe shares the step's loss builder, so it replays
        the exact rng/noise/timesteps of the offending step."""
        from ..telemetry.numerics import nonfinite_modules
        if self._probe is None:
            from .train_step import make_grad_probe
            apply_fn, schedule, transform, kw = self._probe_inputs
            self._probe = jax.jit(make_grad_probe(
                apply_fn, schedule, transform, **kw))
        from ..parallel.context import use_mesh
        # the live state's step counter already advanced past the
        # offending step; rewind it for the probe so the rng fold —
        # and with it noise/timesteps/dropout — replays exactly
        probe_state = self.state.replace(
            step=jnp.maximum(self.state.step - 1, 0))
        with tel.span("numerics.provenance", cat="numerics",
                      args={"step": step}):
            with use_mesh(self.mesh):
                probe = self._probe(probe_state,
                                    self._numeric_subtree(batch))
            modules = nonfinite_modules(probe)
        detail = (f"non-finite values localized to module(s) "
                  f"{modules}" if modules else
                  "no per-module non-finite values found (non-finite "
                  "loss without non-finite grads/params — bad batch?)")
        _res_events.global_event_log().record(
            "nan_provenance", "numerics.provenance",
            detail=detail, step=step)
        tel.write_record({"type": "nan_provenance", "step": int(step),
                          "modules": modules})
        return modules

    def fit(self,
            data: Iterator[PyTree],
            total_steps: int,
            callbacks: Sequence[Callable[[int, float, Dict], None]] = (),
            save_every: Optional[int] = None,
            data_factory: Optional[Callable[[Any], Iterator[PyTree]]]
            = None,
            data_plane: Optional[Any] = None) -> Dict[str, Any]:
        """Run `total_steps` steps from `data` (host-local numpy batches).

        Returns summary metrics. The hot loop is sync-free pipelined:
        dispatch runs up to `pipeline_depth` steps ahead of the device,
        H2D upload rides a background `prefetch_to_device` thread, and
        per-step losses accumulate in a device-resident window read
        back with ONE host sync per `log_every` window — NaN / abnormal
        loss anywhere in the window triggers a rollback to the best
        state seen, and (with `gate_nonfinite`, the default) a poisoned
        update never lands in the state at all. Because upload
        prefetches ahead, up to `pipeline_depth + 1` batches of `data`
        may be consumed-but-unused when fit returns — an accepted cost
        on streaming data (the background worker is joined before
        return, so handing `data` to another consumer afterwards is
        safe).

        `data_factory(world_view) -> iterator` re-shards the input
        pipeline around an elastic world transition (requires the
        trainer's `elastic` manager): after a committed shrink /
        re-admission / eviction the old upload worker is closed and a
        fresh pipeline for the NEW (rank, size) starts. One
        already-prefetched batch from the old shard may still be
        consumed — an accepted off-by-one on streaming data, recorded
        nowhere because it changes nothing the ledger cares about.

        `data_plane` (a `data.dataplane.DataPlane`) supersedes `data`
        with a DETERMINISTIC batch stream: the plane's cursor is the
        replay coordinate. Every rollback (anomaly, quorum, elastic
        restore) closes the upload worker, rewinds the stream to the
        landed step's batch boundary, and rebuilds the pipeline, so
        replayed steps see bit-identical batches; the plane's screen
        gates each batch before H2D upload (poisoned batches are
        quarantined with blast radius one batch); each checkpoint
        commit persists the plane's state through the StepLedger and
        runs the cross-host batch-hash skew vote. With `data_factory`
        too, elastic transitions swap the resharded factory INTO the
        plane (`adopt`) so journal/breaker/digest state survives the
        world change.
        """
        cfg = self.config
        losses, log_t0 = [], time.perf_counter()
        steps_in_window = 0
        pending_loss = None
        loss_window: list = []      # (step_no, device scalar), unfetched
        inflight: list = []         # dispatched-step losses, oldest first
        # In-graph loss ring: the window boundary becomes the ring size
        # (ONE readback per W steps regardless of log_every); per-step
        # device scalars are no longer retained host-side. Slot mapping
        # anchors on the LIVE step counter at fetch time, so resumed
        # fits and mid-run rollbacks (which rewind the counter) stay
        # correct without bookkeeping.
        ring_n = max(cfg.loss_ring, 0)
        if ring_n and self.state.loss_ring is None:
            raise ValueError(
                "TrainerConfig.loss_ring > 0 but the TrainState carries "
                "no ring (state restored from a pre-ring checkpoint?)")
        ring_pending = [0]          # count of steps since the last fetch
        # gate-activation visibility: baseline the cumulative in-graph
        # counter ONCE at fit start (the state is at rest here — a
        # resumed/rolled-back state legitimately carries prior counts),
        # then surface per-window deltas at log cadence
        gate_prev = (_fetch_gate_events(self.state.gate_events)
                     if self.state.gate_events is not None else None)
        peak = device_peak_flops()
        flops = None
        history: Dict[str, Any] = {"steps": [], "loss": [], "imgs_per_sec": [],
                                   "mfu": [], "preempted": False,
                                   "watchdog_fired": False,
                                   "coordination_lost": False,
                                   "elastic": [], "quorum_evicted": False,
                                   "saves": {"started": 0,
                                             "skipped_exists": 0,
                                             "failed": 0}}
        events = _res_events.global_event_log()
        fault_plan = _res_faults.active_plan()
        nan_pending = False     # step.nan fault armed for next loss read
        elastic = self.elastic
        # transition seconds spent INSIDE the checkpoint phase this step
        # (commit-triggered shrink/admit): settle_step subtracts them so
        # the time is attributed once, to its elastic bucket, not twice
        elastic_spent = [0.0]

        # Telemetry: phase timing + goodput attribution always run (an
        # in-memory account on the default hub costs microseconds); the
        # per-step device sync and JSONL rows only under an ENABLED hub
        # — exact device-phase timing requires closing async dispatch
        # with block_until_ready, which trades the one-deep pipeline for
        # attribution. MFU from device-phase time rides the same meter.
        tel = self.telemetry if self.telemetry is not None \
            else _global_telemetry()
        timed = tel.enabled
        device_meter = MFUMeter(peak_flops=peak) if timed else None
        timer = tel.step_timer(mfu_meter=device_meter,
                               sample_every=max(
                                   cfg.telemetry_sample_every, 1))
        goodput = tel.goodput
        # per-fit goodput delta: the hub may be process-global/cumulative
        gp_base_prod, gp_base_bad = goodput.raw_counters()

        # Training-health: the detector owns BOTH the cadence anomaly
        # checks and the historical abnormal-loss trigger (non-finite /
        # <= floor), so fault-injected and real NaNs take one code path.
        from ..telemetry.memory import MemoryMonitor
        from ..telemetry.numerics import AnomalyConfig, AnomalyDetector
        detector = AnomalyDetector(
            AnomalyConfig(zscore=cfg.anomaly_zscore,
                          window=cfg.anomaly_window,
                          abnormal_loss_floor=cfg.abnormal_loss_floor,
                          action=cfg.anomaly_action),
            telemetry=tel)
        memory = MemoryMonitor()
        # Automated device-profile windows (telemetry/devprof.py):
        # built only when configured AND the hub is enabled with a
        # devprof sink — the default path carries no profiler object
        # at all, so un-configured fits see zero change.
        devprof = None
        if timed and getattr(tel, "devprof_path", None) and (
                cfg.profile_cadence > 0
                or cfg.profile_trigger is not None):
            from ..telemetry.devprof import DeviceProfiler
            devprof = DeviceProfiler(
                tel.devprof_path,
                cadence=cfg.profile_cadence,
                window=max(cfg.profile_steps, 1),
                trigger_path=cfg.profile_trigger,
                metrics=tel.registry)
        history["anomalies"] = 0
        last_health = {"grad_norm": None}   # latest cadence grad norm
        provenance_done = False     # the debug re-run happens ONCE per fit
        monitored_compiled = False  # first cadence step pays a 2nd compile

        # Resume-at-start: under coordination this is the consensus
        # round — it must run BEFORE any step so a divergent world
        # raises here, never trains. ConsensusError propagates.
        if cfg.restore_at_start and self.checkpointer is not None:
            try:
                with tel.span("train.restore_at_start", cat="restore"), \
                        goodput.measure_badput("restart"):
                    step0 = self.restore_checkpoint()
                if data_plane is not None:
                    # rewind the stream to the restored step's batch
                    # boundary (journal/breakers reload from the ledger's
                    # data_state entry, so replay skips the same records)
                    data_plane.restore(step0,
                                       ledger=self.checkpointer.ledger)
                events.record("restored", "train.start",
                              detail=f"resumed from step {step0}",
                              step=step0)
            except FileNotFoundError:
                events.record("cold_start", "train.start",
                              detail="no restorable checkpoint; "
                                     "training from scratch")

        def count_save():
            res = (self.checkpointer.last_save_result
                   if self.checkpointer is not None else "none")
            if res in history["saves"]:
                history["saves"][res] += 1

        from ..data.prefetch import prefetch_to_device

        def _new_upload(src):
            """Build the H2D upload worker; with a data plane its screen
            gates every batch BEFORE the put and its journal records the
            quarantined ones."""
            return prefetch_to_device(
                self.put_batch, src, depth=max(cfg.pipeline_depth, 1),
                screen=(data_plane.screen if data_plane is not None
                        else None),
                quarantine=(data_plane.journal if data_plane is not None
                            else None))

        def _rewind_data(step) -> None:
            """Rewind the deterministic data plane to `step`'s batch
            boundary and rebuild the upload pipeline: prefetched-but-
            unconsumed batches are DISCARDED (never replayed out of
            order), and the next batch consumed is exactly batch index
            `step` — the bit-identical replay contract. No-op without a
            data plane or with an unknown landing step (best-state /
            fresh-rng recoveries that never rewound the step counter
            to a determinate boundary keep the stream position)."""
            nonlocal upload, global_batch
            if data_plane is None or step is None:
                return
            upload.close()
            data_plane.seek(int(step))
            upload = _new_upload(data_plane)
            with goodput.measure_badput("data_stall"), \
                    tel.span("data.rewind_refetch", cat="data",
                             args={"step": int(step)}):
                global_batch = next(upload)

        def _adopt_change(change, bucket: str, restore_step, t0: float,
                          in_ckpt_phase: bool) -> None:
            """Common adoption of a committed WorldChange: re-arm the
            coordinator in the new epoch namespace, rebuild the mesh if
            it spanned lost devices, restore the consensus step when
            the transition demands one, swap the data shard, and put
            the transition on the books (goodput bucket + reclaimed
            estimate, elastic/* metrics, JSONL row, history)."""
            nonlocal upload, global_batch
            coord = (self.checkpointer.coordinator
                     if self.checkpointer is not None else None)
            if coord is not None:
                coord.rebirth()
            self._rebuild_world_mesh()
            if restore_step is not None:
                with tel.span("elastic.restore", cat="restore",
                              args={"step": restore_step}):
                    self._elastic_restore(restore_step)
                # the restore rewound the step counter: unfetched loss
                # slots no longer map to live steps
                ring_pending[0] = 0
                loss_window.clear()
                inflight.clear()
            if data_factory is not None and elastic is not None:
                upload.close()
                if data_plane is not None:
                    # swap the resharded factory INTO the plane: the
                    # journal/breaker/digest state survives the world
                    # change, and the surviving view resumes at the
                    # consensus batch boundary — a shrink never
                    # re-serves samples the survivors already consumed
                    data_plane.adopt(
                        data_factory(elastic.world_view()),
                        cursor=(restore_step if restore_step is not None
                                else change.step))
                    upload = _new_upload(data_plane)
                    with goodput.measure_badput("data_stall"):
                        global_batch = next(upload)
                else:
                    upload = prefetch_to_device(
                        self.put_batch, data_factory(elastic.world_view()),
                        depth=max(cfg.pipeline_depth, 1))
            elif restore_step is not None:
                # no factory swap, but the restore rewound the step
                # counter: replay must see the same batches again
                _rewind_data(restore_step)
            dt = time.perf_counter() - t0
            goodput.record_badput(bucket, dt)
            reclaimed = elastic.reclaimed_estimate(change.step, dt,
                                                   goodput=goodput)
            goodput.record_reclaimed(bucket, reclaimed)
            if in_ckpt_phase:
                elastic_spent[0] += dt
            tel.counter("elastic/transitions").inc()
            kind_counter = {"shrink": "elastic/shrinks",
                            "grow": "elastic/readmits",
                            "evict": "elastic/evictions"}.get(change.kind)
            if kind_counter:
                tel.counter(kind_counter).inc()
            tel.gauge("elastic/world_size").set(float(change.world))
            tel.gauge("elastic/epoch").set(float(change.epoch))
            tel.gauge("elastic/last_transition_s").set(dt)
            tel.write_record({
                "type": "elastic_transition", "kind": change.kind,
                "epoch": change.epoch, "world": change.world,
                "members": list(change.members),
                "removed": list(change.removed),
                "added": list(change.added), "step": change.step,
                "duration_s": round(dt, 6),
                "reclaimed_s": round(reclaimed, 6),
                "reason": change.reason})
            history["elastic"].append({
                "kind": change.kind, "epoch": change.epoch,
                "world": change.world, "step": change.step,
                "duration_s": dt, "reclaimed_s": reclaimed})

        def _elastic_shrink(reason: str,
                            in_ckpt_phase: bool = True) -> bool:
            """Shrink-to-survive: returns True when a smaller world was
            committed and adopted (training continues), False when the
            caller must fall back to checkpoint-and-exit."""
            from ..resilience.elastic import ElasticError
            t0 = time.perf_counter()
            try:
                with tel.span("elastic.shrink", cat="elastic",
                              args={"reason": reason}):
                    change = elastic.shrink(reason)
            except ElasticError as e:
                events.record("elastic_error", "elastic.shrink",
                              detail=repr(e))
                return False
            if change is None:
                return False
            _adopt_change(change, bucket="elastic_shrink",
                          restore_step=change.step, t0=t0,
                          in_ckpt_phase=in_ckpt_phase)
            return True

        def _elastic_boundary(committed_step) -> None:
            """Healthy-commit-boundary hooks: the re-admission check.
            KV traffic only — zero device syncs (the counting-mock
            elasticity tests pin this)."""
            from ..resilience.elastic import ElasticError
            t0 = time.perf_counter()
            try:
                change = elastic.maybe_admit(current_step=committed_step)
            except ElasticError as e:
                # a member vanished between the commit ack and this
                # round: same recovery as a commit timeout
                events.record("elastic_error", "elastic.join",
                              detail=repr(e))
                if not _elastic_shrink(f"admission round failed: {e}"):
                    history["coordination_lost"] = True
                    stop["flag"] = True
                return
            if change is not None:
                # members keep their live state (they ARE the consensus
                # step); only the joiner restores
                _adopt_change(change, bucket="elastic_readmit",
                              restore_step=None, t0=t0,
                              in_ckpt_phase=True)

        def _elastic_quorum(hard: bool, step_no: int) -> Optional[str]:
            """Pod anomaly quorum at a collective step (the numerics
            cadence, or — with `numerics_cadence=0` — the log-step
            window fetch): every member votes; a sick-pod majority
            rolls everyone back to the consensus step, an outlier
            minority is evicted. Returns the decision kind (None when
            the round itself failed) so the caller knows whether the
            anomaly was handled collectively."""
            from ..resilience.elastic import ElasticError
            t0 = time.perf_counter()
            try:
                decision = elastic.quorum_round(hard, step=step_no)
            except ElasticError as e:
                events.record("elastic_error", "elastic.quorum",
                              detail=repr(e))
                if not _elastic_shrink(f"quorum round failed: {e}",
                                       in_ckpt_phase=False):
                    history["coordination_lost"] = True
                    stop["flag"] = True
                return None
            if decision.kind == "none":
                return "none"
            tel.write_record({
                "type": "quorum_decision", "kind": decision.kind,
                "step": step_no,
                "votes": {str(k): v for k, v in decision.votes.items()}})
            history.setdefault("quorum", []).append(decision.kind)
            if decision.kind == "rollback_all":
                if decision.step is not None:
                    with tel.span("elastic.quorum_rollback", cat="restore",
                                  args={"step": decision.step}):
                        self._elastic_restore(decision.step)
                    _rewind_data(decision.step)
                else:
                    # pod-sick with nothing committed: best-state path
                    landed = self._recover(float("nan"), step=step_no)
                    _rewind_data(landed)
                ring_pending[0] = 0
                loss_window.clear()
                inflight.clear()
                dt = time.perf_counter() - t0
                goodput.record_badput("quorum_rollback", dt)
                goodput.record_reclaimed(
                    "quorum_rollback",
                    elastic.reclaimed_estimate(decision.step, dt,
                                               goodput=goodput))
                tel.counter("elastic/quorum_rollbacks").inc()
            elif decision.kind == "evicted":
                # this host's anomaly was the outlier: the survivors
                # continue without it — leave WITHOUT committing (the
                # final local save stays uncommitted, exactly like the
                # coordination-lost exit)
                history["quorum_evicted"] = True
                coord = (self.checkpointer.coordinator
                         if self.checkpointer is not None else None)
                if coord is not None:
                    coord.lost = True
                stop["flag"] = True
            elif decision.kind == "evict" and decision.change is not None:
                _adopt_change(decision.change, bucket="quorum_rollback",
                              restore_step=None, t0=t0,
                              in_ckpt_phase=False)
            return decision.kind

        def commit_save(final: bool = False) -> None:
            """Two-phase-commit the save just dispatched (no-op without
            a ledger). A BarrierTimeout means a peer died mid-round:
            with an elastic manager the survivors shrink the world and
            KEEP TRAINING; otherwise (or when the shrink round itself
            cannot complete) mark coordination lost in the history and
            stop — the final local save still happens, uncommitted, on
            the checkpoint-and-exit path instead of hanging in
            collectives. A healthy commit boundary additionally runs
            the re-admission check for parked replacement hosts."""
            if self.checkpointer is None:
                return
            from ..resilience.coordination import BarrierTimeout
            try:
                committed = self.checkpointer.commit_pending()
            except BarrierTimeout:
                if elastic is not None and not final \
                        and _elastic_shrink("commit barrier timeout"):
                    return
                # the coordinator recorded barrier_timeout and marked
                # itself lost; later commits degrade to local skips
                history["coordination_lost"] = True
                if not final:
                    stop["flag"] = True
                return
            if data_plane is not None and committed is not None:
                # data-plane state commits BESIDE the model commit (same
                # ledger), and the cross-host batch-hash vote runs here —
                # KV/ledger traffic only, zero device syncs
                data_plane.commit(committed,
                                  ledger=self.checkpointer.ledger)
            if elastic is not None and not final and not stop["flag"]:
                _elastic_boundary(committed)

        def handle_numerics(step_no: int, aux, step_batch) -> bool:
            """Cadence-step health handling: flatten the aux (the host
            readback), export gauges + the `numerics` JSONL row + HBM
            gauges, run the detector, and on the first HARD (non-finite)
            anomaly run the provenance pass and the configured action.
            Soft z-score anomalies always only warn under `skip_step`
            (state is already donated); under `rollback` only hard
            anomalies roll back — a 6-sigma loss spike is evidence, a
            NaN is proof. Returns whether a hard anomaly was detected
            (the elastic quorum's vote). With an elastic manager the
            `rollback` action is NOT taken unilaterally: one host's
            rollback would silently fork the fleet, so the verdict goes
            to the pod quorum instead."""
            nonlocal provenance_done
            from ..telemetry.numerics import flatten_aux
            flat = flatten_aux(aux)
            last_health["grad_norm"] = flat.get("numerics/grad_norm")
            tel.record_numerics(flat, step=step_no)
            memory.record(tel.registry)
            if flat.get("numerics/skipped", 0.0) > 0:
                tel.counter("numerics/skipped_steps").inc()
                events.record("skip_step", "numerics.skip",
                              detail="non-finite grads/loss; update "
                                     "gated in-graph (state unchanged)",
                              step=step_no)
            anomalies = detector.observe_aux(step_no, flat)
            if not anomalies:
                return False
            history["anomalies"] += len(anomalies)
            hard = [a for a in anomalies if a.hard]
            if hard and not provenance_done:
                provenance_done = True
                self._nan_provenance(step_batch, tel, step_no)
            if hard and cfg.anomaly_action == "rollback" \
                    and elastic is None:
                landed = self._recover(
                    flat.get("numerics/loss", float("nan")), step=step_no)
                # the restore rewound the step counter: unfetched ring
                # slots no longer map to live steps — drop them (the
                # rollback event records what happened to the window)
                ring_pending[0] = 0
                _rewind_data(landed)
            return bool(hard)

        # SIGTERM -> finish the current step, checkpoint, return. Only
        # the main thread may install handlers; elsewhere (e.g. fit
        # driven from a worker thread) preemption safety cannot arm —
        # surfaced as a resilience warning, not a silent skip.
        import signal
        stop = {"flag": False}
        prev_handler = None
        handler_installed = False
        if cfg.checkpoint_on_sigterm:
            def _on_term(signum, frame):
                stop["flag"] = True
                if callable(prev_handler):
                    prev_handler(signum, frame)
            try:
                prev_handler = signal.signal(signal.SIGTERM, _on_term)
                handler_installed = True
            except ValueError:
                events.record(
                    "warning", "train.sigterm",
                    detail="checkpoint_on_sigterm requested but the "
                           "SIGTERM handler could not be installed "
                           "(fit is not running on the main thread); "
                           "preemption safety disabled for this run")

        # Heartbeat watchdog: turns a wedged step/loader into a clean
        # checkpoint-and-exit (resilience/watchdog.py). The "sigterm"
        # action reuses the preemption path above; the kill only fires
        # when the handler is actually installed, else it would be a
        # real termination.
        watchdog = None
        if cfg.watchdog_timeout is not None:
            import os as _os

            from ..resilience.watchdog import Watchdog

            def _on_stall(gap: float):
                history["watchdog_fired"] = True
                stop["flag"] = True
                if cfg.watchdog_action == "sigterm" and handler_installed:
                    _os.kill(_os.getpid(), signal.SIGTERM)
            watchdog = Watchdog(cfg.watchdog_timeout, on_stall=_on_stall,
                                site="train.step", event_log=events)
            watchdog.start()

        profile_ctx = None
        # Clamp the capture window into [1, total_steps] so a short fit
        # with profile_dir set still produces a trace instead of silently
        # never reaching the default start step (the close is handled in
        # `finally` when the window runs past the last step).
        profile_at = max(1, min(cfg.profile_at_step,
                                max(total_steps - cfg.profile_steps + 1, 1)))

        # Pipelined dispatch (the r5 perf lever — BENCH_r05 measured
        # 0.892x the reference binary with per-step host syncs as the
        # named culprit): H2D upload rides a background thread
        # (prefetch_to_device), dispatch runs up to pipeline_depth
        # steps ahead of the device, and the ONLY mandatory host sync
        # is the log-cadence loss-window fetch. try/finally: an
        # exception escaping the loop (exhausted iterator, raising
        # callback) must still restore the SIGTERM handler — a leaked
        # _on_term would swallow every later SIGTERM — close any open
        # profiler trace, and stop the upload worker (it shares the
        # caller's iterator with later consumers).
        # compile-badput bookkeeping for the warm-cache fix: each
        # first-step/compile-step attribution is remembered alongside a
        # bounded sample of steady-state busy times; once steady
        # evidence exists, a "compile" step that was no slower than an
        # ordinary step (persistent compilation cache hit) is
        # re-attributed productive (goodput.reattribute). The old
        # heuristic admitted this bug: "a warm cache mislabels one
        # cheap step".
        compile_busies: list = []
        steady_busies: list = []
        registered_programs: set = set()    # program-evidence dedupe

        def settle_step(idx: int, compile_step: bool = False
                        ) -> Dict[str, float]:
            """Close the step's phase window, emit the per-step row, and
            attribute its wall-clock to the goodput account: host +
            device + residual of step 1 — and of the FIRST
            numerics-cadence step, which compiles the monitored twin —
            is `compile` badput (provisionally: warm-cache first steps
            are re-attributed productive at fit end once steady-state
            steps exist to compare against), later steps are
            productive; data waits are `data_stall`; the checkpoint
            phase is `checkpoint_commit`, or `coordination_lost` when
            this step's commit round timed out discovering a dead
            peer; the `numerics` phase (aux readback + detector + any
            provenance re-run/rollback) is its own badput bucket —
            monitoring overhead must not masquerade as training. With
            `telemetry_sample_every > 1` the device phase is lumpy
            (zero off-sample, a window's worth on-sample): attribution
            is exact at window granularity, not per step."""
            phases = timer.end_step()
            if timed and timer.last_row is not None:
                # one row per SAMPLE WINDOW (== per step at
                # sample_every=1): off-sample steps emit nothing — their
                # phases ride in the sampled step's window sums
                tel.record_step(timer.last_row)
            busy = (phases.get("host", 0.0) + phases.get("device", 0.0)
                    + phases.get("other", 0.0))
            if idx == 0 or compile_step:
                goodput.record_badput("compile", busy)
                compile_busies.append(busy)
            else:
                goodput.record_productive(busy)
                if len(steady_busies) < 512:
                    steady_busies.append(busy)
            goodput.record_badput("data_stall", phases.get("data_wait", 0.0))
            goodput.record_badput("numerics", phases.get("numerics", 0.0))
            # device-profile window overhead (open/close + the close's
            # pipeline drain + capture parse) is measurement, not
            # training — its own bucket keeps the MFU account honest
            goodput.record_badput("profile", phases.get("profile", 0.0))
            # elastic transitions that ran inside this step's checkpoint
            # phase were already attributed to their own bucket
            # (elastic_shrink/elastic_readmit) — subtract them so each
            # second lands in exactly one bucket
            ckpt_s = max(phases.get("checkpoint", 0.0) - elastic_spent[0],
                         0.0)
            elastic_spent[0] = 0.0
            goodput.record_badput(
                "coordination_lost" if history["coordination_lost"]
                else "checkpoint_commit", ckpt_s)
            return phases

        def reclassify_warm_compile() -> None:
            """The compile-badput time-threshold fix: a first step that
            ran no slower than _COMPILE_RECLASS_RATIO x the median
            steady step did not compile (persistent cache hit / an
            already-warm program on a re-entered fit) — move its busy
            time back to productive. Needs >= 3 steady samples; with
            fewer, the conservative (badput) attribution stands."""
            if not compile_busies or len(steady_busies) < 3:
                return
            med = sorted(steady_busies)[len(steady_busies) // 2]
            for busy in compile_busies:
                if busy <= _COMPILE_RECLASS_RATIO * max(med, 1e-9):
                    moved = goodput.reattribute("compile", busy)
                    if moved > 0:
                        events.record(
                            "warm_compile_reclassified", "train.step",
                            detail=f"first-step busy {busy:.3f}s ~ "
                                   f"steady median {med:.3f}s: warm "
                                   "compilation cache; re-attributed "
                                   "productive")
            compile_busies.clear()

        # with a data plane, the plane IS the batch stream (its cursor
        # is the replay coordinate every rollback rewinds to)
        upload = _new_upload(data_plane if data_plane is not None else data)
        try:
            with goodput.measure_badput("data_stall"), \
                    tel.span("data.first_batch", cat="data"):
                global_batch = next(upload)
            for i in range(total_steps):
                if watchdog is not None:
                    watchdog.beat()
                if stop["flag"]:
                    # the post-loop force-save persists the state; here
                    # only mark and stop
                    history["preempted"] = True
                    events.record("preempt", "train.step",
                                  detail="SIGTERM (or watchdog) — "
                                         "checkpointing and returning",
                                  step=i)
                    break
                if fault_plan is not None:
                    # chaos sites (use error="flag" specs): a NaN poisons
                    # the next loss readback so the rollback path runs; a
                    # sigterm exercises the preemption path end-to-end; a
                    # numerics.nan corrupts ONE module's params so the
                    # numerics monitor + provenance pass must catch AND
                    # localize it.
                    if fault_plan.check("step.nan", step=i + 1):
                        nan_pending = True
                    if fault_plan.check("numerics.nan", step=i + 1):
                        self._poison_module_params()
                    if fault_plan.check("host.sigterm", step=i + 1):
                        import os as _os
                        _os.kill(_os.getpid(), signal.SIGTERM)
                if cfg.profile_dir is not None:
                    from ..profiling import trace
                    if i + 1 == profile_at and profile_ctx is None:
                        profile_ctx = trace(cfg.profile_dir)
                        profile_ctx.__enter__()
                    elif (profile_ctx is not None
                            and i + 1 == profile_at + cfg.profile_steps):
                        _block_until_ready(pending_loss)
                        profile_ctx.__exit__(None, None, None)
                        profile_ctx = None
                current = global_batch
                monitored = (self._step_monitored is not None
                             and (i + 1) % cfg.numerics_cadence == 0)
                compile_step = monitored and not monitored_compiled
                fetch_every = ring_n if ring_n else cfg.log_every
                log_step = ((i + 1) % fetch_every == 0
                            or i == total_steps - 1)
                timer.begin_step(i + 1)
                if compile_step or log_step:
                    # these steps close dispatch anyway (twin compile /
                    # window fetch): take the free exact device sample
                    timer.mark_sampled()
                if devprof is not None:
                    # automated profile windows: open BEFORE this
                    # step's dispatch, close before the first dispatch
                    # PAST the window — both inside the `profile`
                    # phase, which settle_step books to its own badput
                    # bucket so window overhead never pollutes MFU.
                    # The close drains the pipeline through the counted
                    # sync seam (every step dispatched inside the
                    # window lands in the capture) and reconciles the
                    # parsed row against the step's registry program;
                    # off-window steps reach neither branch — two int
                    # compares, zero syncs.
                    if devprof.should_close(i + 1):
                        with timer.phase("profile"):
                            if pending_loss is not None:
                                _block_until_ready(pending_loss)
                            inflight.clear()
                            prog_key = self._register_program_evidence(
                                tel, current, registered_programs,
                                (compile_busies[0] if compile_busies
                                 else None),
                                monitored_compiled, flops)
                            devprof.close(i + 1, kind="train_step",
                                          key=prog_key,
                                          programs=tel.programs)
                    elif devprof.should_open(i + 1):
                        with timer.phase("profile"):
                            devprof.open(i + 1)
                if watchdog is not None and (i == 0 or compile_step):
                    # first call of either program pays jit compile —
                    # not a stall
                    watchdog.pause()
                pending_aux = None
                with timer.phase("host"):
                    if monitored:
                        pending_loss, pending_aux = \
                            self.train_step_monitored(current)
                        monitored_compiled = True
                    else:
                        pending_loss = self.train_step(current)
                if watchdog is not None and (i == 0 or compile_step):
                    watchdog.resume()
                if ring_n:
                    ring_pending[0] += 1
                else:
                    loss_window.append((i + 1, pending_loss))
                inflight.append(pending_loss)
                if cfg.pipeline_depth > 0:
                    # bounded in-flight dispatch: the device may lag
                    # the host by at most pipeline_depth steps. The
                    # oldest in-flight step is checked non-blockingly
                    # first — on a healthy pipeline it has long
                    # settled and this costs one host query; only
                    # genuine backpressure (device > depth behind)
                    # waits, and it waits exactly the surplus.
                    while len(inflight) > cfg.pipeline_depth:
                        oldest = inflight.pop(0)
                        if not _is_ready(oldest):
                            tel.counter("pipeline/backpressure_waits").inc()
                            _block_until_ready(oldest)
                if i + 1 < total_steps:
                    with timer.phase("data_wait"):
                        global_batch = next(upload)
                if timed and timer.sampled:
                    # close async dispatch so the device phase is real
                    # device time, not whatever later host op happens to
                    # block first (the async-dispatch lie). In sampled
                    # mode (telemetry_sample_every > 1) only sampled
                    # steps pay this sync; their device phase covers
                    # every step dispatched since the previous sample.
                    with timer.phase("device"):
                        _block_until_ready(pending_loss)
                    inflight.clear()    # everything older has settled
                if pending_aux is not None:
                    # the one host sync a cadence step pays: aux
                    # readback, gauges + JSONL row, detector verdicts,
                    # and (first hard anomaly only) provenance + action
                    with timer.phase("numerics"):
                        hard_anomaly = handle_numerics(i + 1, pending_aux,
                                                       current)
                    if elastic is not None \
                            and cfg.anomaly_action == "rollback":
                        # the pod quorum rides the numerics cadence —
                        # every member reaches this step in lockstep, so
                        # the vote is collective by construction. KV
                        # traffic only; its time lands in the `elastic`
                        # phase, attributed to quorum_rollback when a
                        # decision fires.
                        with timer.phase("elastic"):
                            _elastic_quorum(bool(hard_anomaly), i + 1)
                steps_in_window += 1

                recovered = False
                if log_step:
                    # THE one mandatory host sync of the window: fetch
                    # the device-resident loss window (blocks until the
                    # newest step settles, so it also closes dispatch —
                    # this step was marked sampled above and the wait
                    # landed in the device phase already).
                    inflight.clear()
                    if ring_n:
                        # one device_get of the in-graph ring covers the
                        # whole window; the newest r steps wrote slots
                        # (step_now - r) .. (step_now - 1) mod W
                        ring_vals = _fetch_ring(self.state.loss_ring)
                        step_now = int(jax.device_get(self.state.step))
                        r = min(ring_pending[0], ring_n)
                        vals = [float(ring_vals[(step_now - r + t) % ring_n])
                                for t in range(r)]
                        ring_pending[0] = 0
                    else:
                        window = loss_window
                        loss_window = []
                        vals = _fetch_losses([v for _, v in window])
                    if not vals:
                        # an elastic transition (quorum rollback /
                        # shrink restore) emptied the window mid-cadence:
                        # every retained slot mapped to a rewound step.
                        # Nothing to report; treat like a recovery so
                        # the save guard below re-arms on fresh steps.
                        steps_in_window = 0
                        log_t0 = time.perf_counter()
                        recovered = True
                    if nan_pending and vals:
                        vals[-1], nan_pending = float("nan"), False
                    if gate_prev is not None \
                            and self.state.gate_events is not None:
                        # per-window delta of the in-graph gate counter
                        # (the window fetch above already settled the
                        # pipeline; this read costs no extra sync).
                        # Clamped at 0: a rollback rewinds the
                        # cumulative counter below the baseline.
                        ge = _fetch_gate_events(self.state.gate_events)
                        delta = np.maximum(ge - gate_prev, 0)
                        gate_prev = ge
                        if int(delta.sum()):
                            tel.counter("numerics/gate_activations") \
                                .inc(int(delta.sum()))
                            for part, d in zip(
                                    ("params", "opt_state", "ema"),
                                    delta):
                                if int(d):
                                    tel.counter(
                                        f"numerics/gate_activations/"
                                        f"{part}").inc(int(d))
                            events.record(
                                "gate_activated", "train.step",
                                detail=f"in-graph non-finite gate "
                                       f"masked {int(delta.sum())} "
                                       f"element(s) this window "
                                       f"(params/opt/ema = "
                                       f"{delta.tolist()})",
                                step=i + 1)
                    # Mid-window non-finite losses are VISIBILITY, not a
                    # verdict: with the in-graph gate a poisoned batch's
                    # update never landed, so a finite cadence loss
                    # means the state recovered on its own (the
                    # skip_step contract) — recovery stays keyed to the
                    # cadence-step loss exactly as before, but the
                    # window now shows transients the old single-value
                    # fetch could never see.
                    n_bad = sum(1 for v in vals[:-1]
                                if not np.isfinite(v))
                    if n_bad:
                        gated = ("; update(s) withheld in-graph"
                                 if cfg.gate_nonfinite else "")
                        events.record(
                            "window_nonfinite", "train.step",
                            detail=f"{n_bad} non-finite loss(es) inside "
                                   f"the window ending at step "
                                   f"{i + 1}{gated}",
                            step=i + 1)
                    # ONE code path for fault-injected and real NaNs:
                    # the detector's hard triggers subsume the old
                    # `isfinite or <= floor` ad-hoc check
                    loss = vals[-1] if vals else float("nan")
                    anomaly = (None if recovered
                               else detector.abnormal_loss(loss,
                                                           step=i + 1))
                    if not recovered and elastic is not None \
                            and cfg.anomaly_action == "rollback" \
                            and cfg.numerics_cadence == 0:
                        # numerics_cadence=0 quorum hole, closed: with
                        # no cadence step the hard verdict surfaces
                        # HERE, and a unilateral local rollback would
                        # silently fork the pod. Every member reaches
                        # every log step in lockstep, so the vote is
                        # collective by construction — healthy members
                        # vote False each window, the anomalous one
                        # votes True, and the pod decides together
                        # (rollback_all restores + clears the window
                        # inside _elastic_quorum). A failed round never
                        # falls back to the unilateral path: that is
                        # the fork this guard exists to prevent.
                        with timer.phase("elastic"):
                            verdict = _elastic_quorum(
                                anomaly is not None, i + 1)
                        if anomaly is not None \
                                or verdict in ("rollback_all", "evicted"):
                            steps_in_window = 0
                            log_t0 = time.perf_counter()
                            recovered = True
                    if recovered:
                        pass    # transition emptied the window above
                    elif anomaly is not None:
                        landed = self._recover(loss, step=i + 1)
                        _rewind_data(landed)
                        steps_in_window = 0
                        log_t0 = time.perf_counter()
                        recovered = True
                    else:
                        losses.append(loss)
                        dt = time.perf_counter() - log_t0
                        # global batch size: `current` holds global
                        # sharded arrays, so the leading dim IS the
                        # global batch (no process_count multiply)
                        bsz = jax.tree_util.tree_leaves(
                            current)[0].shape[0]
                        ips = steps_in_window * bsz / max(dt, 1e-9)
                        if flops is None and peak:
                            flops = self.step_flops(global_batch)
                        step_mfu = (mfu(flops, dt / steps_in_window, peak)
                                    if flops else None)
                        if tel.programs is not None:
                            # program evidence registry: one row per
                            # compiled step program, at the first log
                            # window (plus the monitored twin once it
                            # has compiled) — per-program roofline
                            # attribution beside the global mfu gauges
                            self._register_program_evidence(
                                tel, global_batch, registered_programs,
                                (compile_busies[0] if compile_busies
                                 else None),
                                monitored_compiled, flops)
                        window_steps = steps_in_window
                        steps_in_window = 0
                        history["steps"].append(i + 1)
                        history["loss"].append(loss)
                        history["imgs_per_sec"].append(ips)
                        history["mfu"].append(step_mfu)
                        metrics = {"imgs_per_sec": ips}
                        finite = [v for v in vals if np.isfinite(v)]
                        if finite:
                            # the window fetch makes every step's loss
                            # visible at no extra sync: report the
                            # window mean beside the spot value
                            metrics["loss_window_mean"] = \
                                float(np.mean(finite))
                        if ring_n and len(vals) <= 64:
                            # retroactive per-step visibility: the
                            # JsonlLogger serializes small numeric seqs,
                            # so log_every=1 users still get every
                            # step's loss — delivered once per window
                            metrics["window_losses"] = list(vals)
                        if step_mfu is not None:
                            metrics["mfu"] = step_mfu
                        if timed and flops and device_meter.steps:
                            # utilization against DEVICE time (phase-
                            # timed), not end-to-end step time: the gap
                            # between the two numbers IS the host/input
                            # overhead the phase breakdown localizes
                            device_meter.flops_per_step = flops
                            mfu_dev = device_meter.mfu()
                            if mfu_dev is not None:
                                metrics["mfu_device"] = mfu_dev
                        # resilience counters ride the normal metric
                        # stream (JSONL/wandb via the callback's logger)
                        metrics.update(events.summary())
                        for cb in callbacks:
                            cb(i + 1, loss, metrics)
                        if cfg.keep_best_state and loss < self.best_loss:
                            self.best_loss = loss
                            self.best_state = jax.tree_util.tree_map(
                                jnp.copy, self.state)
                            self.best_step = i + 1
                        if timed:
                            tel.gauge("train/loss").set(loss)
                            tel.gauge("train/imgs_per_sec").set(ips)
                            # HBM gauges ride the log cadence even when
                            # the numerics monitor is off (host-only
                            # allocator read; self-disables off-TPU)
                            memory.record(tel.registry)
                            # pod-wide skew: every host contributes its
                            # window means; rank 0 logs min/max/p50/p99.
                            # A collective — all hosts hit log cadence
                            # in lockstep (same SPMD-driver assumption
                            # as the commit rounds).
                            agg = {"step_time": dt / max(window_steps, 1),
                                   "imgs_per_sec": ips, "loss": loss}
                            if last_health["grad_norm"] is not None:
                                # pod/grad_norm/spread: divergence skew —
                                # one host drifting shows before it NaNs
                                agg["grad_norm"] = last_health["grad_norm"]
                            if timer.last is not None:
                                agg["data_wait"] = timer.last.get(
                                    "data_wait", 0.0)
                                agg["device_time"] = timer.last.get(
                                    "device", 0.0)
                            tel.aggregate(agg, step=i + 1)
                            tel.export(step=i + 1)
                        log_t0 = time.perf_counter()

                if not recovered and save_every and (i + 1) % save_every == 0:
                    # "Never checkpoint a NaN" (VERDICT r1 weak #4),
                    # rebuilt sync-free: with gate_nonfinite (default)
                    # the in-graph gate withheld any non-finite update,
                    # so the live state is finite BY CONSTRUCTION and
                    # the save needs no loss fetch — the old
                    # float(pending_loss) here was a forced pipeline
                    # serialization every save_every steps. Without the
                    # gate, the legacy synchronous check stands: the
                    # fetch is then the only protection.
                    with timer.phase("checkpoint"):
                        do_save = True
                        if not cfg.gate_nonfinite:
                            loss_now = _fetch_losses([pending_loss])[0]
                            if nan_pending:
                                loss_now, nan_pending = float("nan"), False
                            if detector.abnormal_loss(
                                    loss_now, step=i + 1) is not None:
                                landed = self._recover(loss_now, step=i + 1)
                                ring_pending[0] = 0   # slots rewound
                                _rewind_data(landed)
                                do_save = False
                        if do_save:
                            with tel.span("ckpt.save_and_commit",
                                          cat="checkpoint",
                                          args={"step": i + 1}):
                                self.save_checkpoint()
                                count_save()
                                commit_save()
                            goodput.persist()
                settle_step(i, compile_step=compile_step)
                if devprof is not None and log_step:
                    # on-demand arming rides the log cadence (one host
                    # stat per window, zero cost on other steps): an
                    # existing trigger file opens a window next step
                    devprof.poll_trigger()

            # The final save can legitimately outlast the watchdog timeout
            # (sync flush of an async save) — stand the watchdog down
            # first so it cannot SIGTERM a healthy shutdown.
            if watchdog is not None:
                watchdog.stop()
            # warm-cache compile fix: with steady-state evidence in
            # hand, re-attribute "compile" first steps that ran at
            # ordinary speed BEFORE the account is flushed/persisted
            reclassify_warm_compile()
            # Final force-save runs BEFORE the handler restore in `finally`:
            # a second SIGTERM arriving during this save — the exact window
            # preemption handling exists to protect — must hit _on_term (a
            # harmless re-mark of stop["flag"]), not the default action.
            with tel.span("ckpt.final_save", cat="checkpoint"), \
                    goodput.measure_badput(
                        "coordination_lost" if history["coordination_lost"]
                        else "checkpoint_commit"):
                self.save_checkpoint(force=True)
                count_save()
                commit_save(final=True)
        finally:
            # stop the upload worker FIRST: the caller may hand the
            # source iterator to another consumer (validation) the
            # moment fit returns, and two threads driving one generator
            # is a race (close() joins the worker, bounded)
            upload.close()
            if watchdog is not None:
                watchdog.stop()
            if profile_ctx is not None:
                # sync before closing so async-dispatched steps' device
                # activity lands in the trace (windows that run past the
                # last step close here instead of in-loop)
                if pending_loss is not None:
                    _block_until_ready(pending_loss)
                profile_ctx.__exit__(None, None, None)
            if devprof is not None and devprof.active():
                # a cadence window still open past the last step:
                # drain, close and parse it here so the capture still
                # becomes a devprof row — attributed to the same
                # `profile` bucket as in-loop closes
                with goodput.measure_badput("profile"):
                    if pending_loss is not None:
                        _block_until_ready(pending_loss)
                    devprof.close(kind="train_step",
                                  programs=tel.programs)
            if handler_installed:
                signal.signal(signal.SIGTERM,
                              prev_handler if prev_handler is not None
                              else signal.SIG_DFL)
            # persist trace + goodput even on an exceptional exit — the
            # post-mortem needs the account most exactly then. I/O
            # failure must not mask the original exception.
            try:
                tel.flush()
            except OSError as e:
                events.record("telemetry_lost", "telemetry.flush",
                              detail=repr(e))
        history["final_loss"] = losses[-1] if losses else float("nan")
        history["best_loss"] = self.best_loss
        history["resilience"] = events.summary()
        prod, bad = goodput.raw_counters()
        history["goodput"] = {
            "productive_s": prod - gp_base_prod,
            "badput_s": {k: round(v - gp_base_bad.get(k, 0.0), 6)
                         for k, v in bad.items()
                         if v - gp_base_bad.get(k, 0.0) > 0.0}}
        return history

    def _recover(self, bad_loss: float,
                 step: Optional[int] = None) -> Optional[int]:
        """Abnormal-loss / anomaly recovery (reference
        simple_trainer.py:542-575): restore the best state if we have
        one; with no best state yet but a checkpointer holding a
        restorable step, walk back to it (the PR-1/2 fallback-restore
        path — corrupt newer steps are skipped, ledger mode restores
        only committed steps). Only with neither does the run continue
        on a fresh rng fold.

        Returns the step the run landed on (the best state's snapshot
        step / the restored checkpoint step), or None when it continued
        in place — the data plane rewinds its stream to this boundary
        so replayed steps see bit-identical batches."""
        tel = self.telemetry if self.telemetry is not None \
            else _global_telemetry()
        if self.best_state is not None:
            _res_events.global_event_log().record(
                "rollback", "train.step",
                detail=f"abnormal loss {bad_loss}; restored best state",
                step=step)
            with tel.span("train.rollback", cat="restore",
                          args={"step": step, "loss": repr(bad_loss)}):
                self.state = jax.tree_util.tree_map(jnp.copy,
                                                    self.best_state)
            return self.best_step
        if self.checkpointer is not None \
                and self.checkpointer.latest_step() is not None:
            with tel.span("train.rollback", cat="restore",
                          args={"step": step, "loss": repr(bad_loss),
                                "source": "checkpoint"}):
                restored = self.restore_checkpoint()
            _res_events.global_event_log().record(
                "rollback", "train.step",
                detail=f"abnormal loss {bad_loss}; no best state — "
                       f"restored checkpoint step {restored}",
                step=step)
            return restored
        _res_events.global_event_log().record(
            "rollback", "train.step",
            detail=f"abnormal loss {bad_loss}; no best state — "
                   "continuing with fresh rng fold",
            step=step)
        # keep going with fresh RNG fold — the step folds rng by step
        # counter, so the next batch draws different noise.
        return None

    # -- inference-side helpers ---------------------------------------------
    def get_params(self, use_ema: bool = True) -> PyTree:
        params = (self.state.ema_params
                  if use_ema and self.state.ema_params is not None
                  else self.state.params)
        if self._param_template is not None:
            # flat-params mode: callers (samplers, validation, export)
            # expect the structured tree
            from .optim import unflatten_params
            return unflatten_params(self._param_template, params)
        return params

"""Serving subsystem: a batched sampler scheduler in front of
`DiffusionInferencePipeline` (docs/SERVING.md).

    scheduler    thread-safe queue -> micro-batch rounds with
                 continuous admission (per-row NFE masking), bucketed
                 padding, bounded in-flight dispatch, deadline
                 shedding, fault-isolated rounds
    engine       compiled-program cache over the single-scan
                 DiffusionSampler, keyed so repeat traffic never
                 re-traces; per-request device carries
    supervision  fault taxonomy (`ServingFault`/`classify`), engine
                 supervision/rebuild (`EngineSupervisor`), brownout
                 degradation (`BrownoutPolicy`) — docs/SERVING.md
                 "Failure semantics"
    loadgen      seeded Poisson workload build + replay (bench.py serve)

SLO metrics ride the telemetry registry under `serving/*`
(docs/OBSERVABILITY.md).
"""
from .engine import (DEFAULT_BATCH_BUCKETS, RequestState,
                     SamplerProgramEngine, bucket_up, nfe_bucket)
from .loadgen import PoissonWorkloadSpec, build_workload, replay
from .request import (DeadlineExceeded, SampleRequest, SampleResult,
                      SchedulerClosed, ServingFuture)
from .scheduler import MS_BUCKET_BOUNDS, SchedulerConfig, ServingScheduler
from .supervision import (BrownoutConfig, BrownoutPolicy, DeviceLost,
                          EngineSupervisor, ServingFault, classify)

__all__ = [
    "BrownoutConfig", "BrownoutPolicy", "DEFAULT_BATCH_BUCKETS",
    "DeadlineExceeded", "DeviceLost", "EngineSupervisor",
    "MS_BUCKET_BOUNDS", "PoissonWorkloadSpec", "RequestState",
    "SampleRequest", "SampleResult", "SamplerProgramEngine",
    "SchedulerClosed", "SchedulerConfig", "ServingFault",
    "ServingFuture", "ServingScheduler", "bucket_up", "build_workload",
    "classify", "nfe_bucket", "replay",
]
